"""Public Serve API.

Role-equivalent of python/ray/serve/api.py :: @serve.deployment /
serve.run / .bind() / serve.status / serve.shutdown (SURVEY §2.6, §3.4).
`Deployment.bind(...)` builds an Application graph (bound sub-deployments
become handles at replica init — model composition); `serve.run` ships the
graph to the singleton controller and returns the ingress handle.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import ray_tpu
from ray_tpu.serve._private.common import (
    CONTROLLER_NAME,
    DEFAULT_APP_NAME,
    AutoscalingConfig,
    DeploymentConfig,
    RetryPolicy,
)
from ray_tpu.serve.handle import DeploymentHandle, _HandlePlaceholder

_proxy_handle = None
_proxy_port: Optional[int] = None
_grpc_handle = None
_grpc_port: Optional[int] = None
# Extra HTTP proxies from start(num_proxies=N): [(port, handle)].
_extra_proxies: list = []


class Application:
    """A bound deployment DAG node (reference: serve's built Application)."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def _collect(self, app_name: str, seen: dict) -> list[dict]:
        """Topo-sort bound nodes into deployment specs (dependencies first)."""
        specs: list[dict] = []

        def resolve(obj: Any) -> Any:
            if isinstance(obj, Application):
                for spec in obj._collect(app_name, seen):
                    if spec["name"] not in [s["name"] for s in specs]:
                        specs.append(spec)
                return _HandlePlaceholder(obj.deployment.name, app_name)
            if isinstance(obj, tuple):
                return tuple(resolve(x) for x in obj)
            if isinstance(obj, list):
                return [resolve(x) for x in obj]
            if isinstance(obj, dict):
                return {k: resolve(v) for k, v in obj.items()}
            return obj

        if self.deployment.name in seen:
            return specs
        seen[self.deployment.name] = True
        init_args = resolve(self.args)
        init_kwargs = resolve(self.kwargs)
        specs.append(
            {
                "name": self.deployment.name,
                "cls_or_fn": self.deployment.func_or_class,
                "init_args": init_args,
                "init_kwargs": init_kwargs,
                "config": self.deployment._config,
                "route_prefix": self.deployment._route_prefix,
            }
        )
        return specs


class Deployment:
    def __init__(
        self,
        func_or_class: Any,
        name: str,
        config: DeploymentConfig,
        route_prefix: Optional[str] = None,
    ):
        self.func_or_class = func_or_class
        self.name = name
        self._config = config
        self._route_prefix = route_prefix

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def options(self, **overrides) -> "Deployment":
        import copy

        config = copy.deepcopy(self._config)
        route_prefix = overrides.pop("route_prefix", self._route_prefix)
        name = overrides.pop("name", self.name)
        for key, value in overrides.items():
            if key == "autoscaling_config" and isinstance(value, dict):
                value = AutoscalingConfig(**value)
            if key == "retry_policy" and isinstance(value, dict):
                value = RetryPolicy.from_dict(value)
            if not hasattr(config, key):
                raise TypeError(f"unknown deployment option {key!r}")
            setattr(config, key, value)
        return Deployment(self.func_or_class, name, config, route_prefix)

    def __repr__(self):
        return f"Deployment({self.name})"


def deployment(
    _func_or_class: Any = None,
    *,
    name: Optional[str] = None,
    num_replicas: int | str | None = None,
    max_ongoing_requests: int = 100,
    user_config: Any = None,
    autoscaling_config: AutoscalingConfig | dict | None = None,
    ray_actor_options: dict | None = None,
    health_check_period_s: float = 10.0,
    health_check_timeout_s: float = 30.0,
    route_prefix: Optional[str] = None,
    request_timeout_s: float = 60.0,
    health_probe_timeout_s: float = 5.0,
    max_queued_requests: int = -1,
    retry_policy: RetryPolicy | dict | None = None,
    graceful_shutdown_timeout_s: float = 20.0,
):
    """@serve.deployment — same shapes as the reference decorator."""

    def wrap(target):
        if isinstance(autoscaling_config, dict):
            asc = AutoscalingConfig(**autoscaling_config)
        else:
            asc = autoscaling_config
        if isinstance(retry_policy, dict):
            policy = RetryPolicy.from_dict(retry_policy)
        else:
            policy = retry_policy or RetryPolicy()
        n_replicas = num_replicas
        if n_replicas == "auto":
            n_replicas = None
            asc = asc or AutoscalingConfig()
        config = DeploymentConfig(
            num_replicas=n_replicas or 1,
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config,
            autoscaling_config=asc,
            ray_actor_options=ray_actor_options or {},
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
            request_timeout_s=request_timeout_s,
            health_probe_timeout_s=health_probe_timeout_s,
            max_queued_requests=max_queued_requests,
            retry_policy=policy,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
        )
        return Deployment(
            target,
            name or getattr(target, "__name__", "deployment"),
            config,
            route_prefix,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


# ---------------------------------------------------------------------------
# cluster-facing API
# ---------------------------------------------------------------------------

def _get_or_create_controller():
    from ray_tpu.serve._private.controller import ServeController

    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    try:
        return (
            ray_tpu.remote(ServeController)
            .options(
                name=CONTROLLER_NAME, lifetime="detached",
                # High: every serve process keeps one async poll_update
                # parked here (coroutine-cheap since async actor methods
                # don't hold executor threads).
                max_concurrency=256,
            )
            .remote()
        )
    except ValueError:
        # Raced with another creator.
        return ray_tpu.get_actor(CONTROLLER_NAME)


def _get_or_create_proxy(proxy_cls, name: str, ready_method: str, *args):
    """Name-keyed get-or-create of a detached proxy actor, race-safe (the
    _get_or_create_controller pattern), blocking until it serves."""
    try:
        handle = ray_tpu.get_actor(name)
    except ValueError:
        try:
            handle = (
                ray_tpu.remote(proxy_cls)
                .options(name=name, lifetime="detached", max_concurrency=64)
                .remote(*args)
            )
        except ValueError:
            # Raced with another creator of the same named actor.
            handle = ray_tpu.get_actor(name)
    ray_tpu.get(getattr(handle, ready_method).remote(), timeout=60)
    return handle


def _kill_quietly(handle) -> None:
    if handle is not None:
        try:
            ray_tpu.kill(handle)
        except Exception:  # rtlint: disable=swallowed-exception - actor already dead
            pass


def _register_proxy(controller, name: str, protocol: str, host: str,
                    port: int) -> None:
    """Hand the proxy to the controller's lifecycle manager (health-check +
    restart-on-death + membership publication for client failover)."""
    try:
        ray_tpu.get(
            controller.register_proxy.remote(name, protocol, host, port),
            timeout=30,
        )
    except Exception:  # rtlint: disable=swallowed-exception - older controller without the registry; proxy still serves, just unmanaged
        pass


def start(
    http_host: str = "127.0.0.1",
    http_port: Optional[int] = 8000,
    grpc_port: Optional[int] = None,
    num_proxies: int = 1,
):
    """Start controller + ingress (reference: serve.start). ``http_port``
    None leaves any existing HTTP proxy untouched; ``grpc_port`` starts a
    gRPC ingress beside the HTTP one (reference: the proxy's dual
    HTTP+gRPC servers). Changing a port replaces (kills) the previous
    proxy on the old port. ``num_proxies`` > 1 starts that many HTTP
    proxies on consecutive ports (ISSUE 13 multi-proxy ingress): each is
    registered with the controller, which health-checks and restarts them;
    clients fail over between the published addresses."""
    global _proxy_handle, _proxy_port, _grpc_handle, _grpc_port
    controller = _get_or_create_controller()
    if http_port is not None and (
        _proxy_handle is None or _proxy_port != http_port
    ):
        from ray_tpu.serve._private.proxy import HTTPProxy

        if _proxy_port is not None and _proxy_port != http_port:
            _kill_quietly(_proxy_handle)
        _proxy_handle = _get_or_create_proxy(
            HTTPProxy, f"SERVE_PROXY::{http_port}", "ready",
            http_host, http_port,
        )
        _proxy_port = http_port
        _register_proxy(
            controller, f"SERVE_PROXY::{http_port}", "http",
            http_host, http_port,
        )
    if http_port is not None and num_proxies > 1:
        from ray_tpu.serve._private.proxy import HTTPProxy

        have = {port for port, _ in _extra_proxies}
        for extra_port in range(http_port + 1, http_port + num_proxies):
            if extra_port in have:
                continue
            name = f"SERVE_PROXY::{extra_port}"
            handle = _get_or_create_proxy(
                HTTPProxy, name, "ready", http_host, extra_port
            )
            _extra_proxies.append((extra_port, handle))
            _register_proxy(controller, name, "http", http_host, extra_port)
    if grpc_port is not None and (
        _grpc_handle is None or _grpc_port != grpc_port
    ):
        from ray_tpu.serve._private.grpc_proxy import GRPCProxy

        if _grpc_port is not None and _grpc_port != grpc_port:
            _kill_quietly(_grpc_handle)
        _grpc_handle = _get_or_create_proxy(
            GRPCProxy, f"SERVE_GRPC_PROXY::{grpc_port}", "get_num_requests",
            http_host, grpc_port,
        )
        _grpc_port = grpc_port
        _register_proxy(
            controller, f"SERVE_GRPC_PROXY::{grpc_port}", "grpc",
            http_host, grpc_port,
        )
    return controller


def run(
    target: Application,
    *,
    name: str = DEFAULT_APP_NAME,
    route_prefix: Optional[str] = "/",
    _blocking_timeout_s: float = 120.0,
    http_port: Optional[int] = None,
    grpc_port: Optional[int] = None,
) -> DeploymentHandle:
    """Deploy an application; block until running; return ingress handle."""
    from ray_tpu._private import usage

    usage.record_feature("serve")
    if not isinstance(target, Application):
        raise TypeError("serve.run expects Deployment.bind(...) output")
    if http_port is not None or grpc_port is not None:
        # http_port=None: leave whatever HTTP proxy exists alone (a
        # grpc-only run must not repoint/recreate the HTTP ingress).
        start(http_port=http_port, grpc_port=grpc_port)
    else:
        controller = _get_or_create_controller()
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    specs = target._collect(name, {})
    ray_tpu.get(
        controller.deploy_application.remote(name, specs, route_prefix),
        timeout=60,
    )
    # Block until every deployment reports enough running replicas.
    deadline = time.time() + _blocking_timeout_s
    while time.time() < deadline:
        status = ray_tpu.get(controller.get_status.remote(), timeout=30)
        app = status.get(name)
        if app and app["status"] == "RUNNING":
            break
        time.sleep(0.2)
    else:
        raise TimeoutError(f"application {name!r} did not become RUNNING")
    return DeploymentHandle(target.deployment.name, name)


def run_from_config(path_or_schema) -> dict:
    """Deploy applications from a YAML file / dict / ServeDeploySchema
    (reference: `serve deploy config.yaml` + serve.run on a built app)."""
    from ray_tpu.serve import schema as schema_mod

    schema = path_or_schema
    if isinstance(schema, str):
        schema = schema_mod.ServeDeploySchema.from_yaml(schema)
    elif isinstance(schema, dict):
        schema = schema_mod.ServeDeploySchema.from_dict(schema)
    return schema_mod.deploy_from_config(schema)


def get_app_handle(name: str = DEFAULT_APP_NAME) -> DeploymentHandle:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    status = ray_tpu.get(controller.get_status.remote(), timeout=30)
    if name not in status:
        raise ValueError(f"no application {name!r}")
    routes = ray_tpu.get(controller.get_routes.remote(), timeout=30)
    for _, qualified in routes.items():
        app, dep = qualified.split("_", 1)
        if app == name:
            return DeploymentHandle(dep, name)
    deployments = list(status[name]["deployments"])
    return DeploymentHandle(deployments[-1], name)


def get_deployment_handle(
    deployment_name: str, app_name: str = DEFAULT_APP_NAME
) -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def status() -> dict:
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return {}
    return ray_tpu.get(controller.get_status.remote(), timeout=30)


def delete(name: str) -> None:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ray_tpu.get(controller.delete_application.remote(name), timeout=60)


def shutdown() -> None:
    global _proxy_handle, _proxy_port, _grpc_handle, _grpc_port
    from ray_tpu.serve._private.long_poll import reset_subscriber

    reset_subscriber()
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=60)
        ray_tpu.kill(controller)
    except Exception:  # rtlint: disable=swallowed-exception - controller already dead at shutdown
        pass
    _kill_quietly(_proxy_handle)
    _kill_quietly(_grpc_handle)
    for _, handle in _extra_proxies:
        _kill_quietly(handle)
    _extra_proxies.clear()
    _proxy_handle = None
    _proxy_port = None
    _grpc_handle = None
    _grpc_port = None

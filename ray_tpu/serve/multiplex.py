"""@serve.multiplexed — per-replica LRU of loaded models.

Role-equivalent of python/ray/serve/multiplex.py (SURVEY §2.6): a replica
lazily loads up to N models keyed by the request's multiplexed_model_id;
least-recently-used models are evicted (calling their __del__/unload). The
router steers by model id when possible via DeploymentHandle.options(
multiplexed_model_id=...).
"""

from __future__ import annotations

import asyncio
import collections
import functools
import inspect
from typing import Callable

from ray_tpu.serve._private.replica import get_current_request_metadata


def get_multiplexed_model_id() -> str:
    meta = get_current_request_metadata()
    if meta is None:
        return ""
    return meta.get("multiplexed_model_id", "")


# Every @multiplexed decorator's cache map, so a draining replica can
# checkpoint its loaded models before the process exits (ISSUE 13).
_ALL_CACHES: list = []


async def checkpoint_loaded_models() -> int:
    """Call ``checkpoint``/``__serve_checkpoint__`` on every model loaded
    through @multiplexed in this process. Returns how many models were
    checkpointed; per-model failures are logged and skipped (a drain must
    not wedge on one broken model)."""
    import logging

    count = 0
    for caches in _ALL_CACHES:
        for cache in caches.values():
            for model_id, model in list(cache.items()):
                hook = getattr(model, "checkpoint", None) or getattr(
                    model, "__serve_checkpoint__", None
                )
                if hook is None:
                    continue
                try:
                    result = hook()
                    if inspect.iscoroutine(result):
                        await result
                    count += 1
                except Exception as exc:
                    logging.getLogger(__name__).warning(
                        "checkpoint of multiplexed model %r failed: %s",
                        model_id, exc,
                    )
    return count


def multiplexed(
    _fn: Callable | None = None, *, max_num_models_per_replica: int = 3
):
    """Decorator on `async def load(self, model_id) -> model`."""

    def decorator(load_fn: Callable):
        caches: dict[int, "collections.OrderedDict"] = {}
        locks: dict[int, asyncio.Lock] = {}
        _ALL_CACHES.append(caches)

        @functools.wraps(load_fn)
        async def wrapper(*args):
            # args = (self, model_id) for methods, (model_id,) for functions
            key = id(args[0]) if len(args) > 1 else 0
            model_id = args[-1]
            cache = caches.setdefault(key, collections.OrderedDict())
            lock = locks.setdefault(key, asyncio.Lock())
            async with lock:
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = load_fn(*args)
                if inspect.iscoroutine(model):
                    model = await model
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    _, evicted = cache.popitem(last=False)
                    unload = getattr(evicted, "unload", None) or getattr(
                        evicted, "__serve_unload__", None
                    )
                    if unload is not None:
                        result = unload()
                        if inspect.iscoroutine(result):
                            await result
                return model

        return wrapper

    if _fn is not None:
        return decorator(_fn)
    return decorator

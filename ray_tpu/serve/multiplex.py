"""@serve.multiplexed — per-replica LRU of loaded models.

Role-equivalent of python/ray/serve/multiplex.py (SURVEY §2.6): a replica
lazily loads up to N models keyed by the request's multiplexed_model_id;
least-recently-used models are evicted (calling their __del__/unload). The
router steers by model id when possible via DeploymentHandle.options(
multiplexed_model_id=...).
"""

from __future__ import annotations

import asyncio
import collections
import functools
import inspect
from typing import Callable

from ray_tpu.serve._private.replica import get_current_request_metadata


def get_multiplexed_model_id() -> str:
    meta = get_current_request_metadata()
    if meta is None:
        return ""
    return meta.get("multiplexed_model_id", "")


def multiplexed(
    _fn: Callable | None = None, *, max_num_models_per_replica: int = 3
):
    """Decorator on `async def load(self, model_id) -> model`."""

    def decorator(load_fn: Callable):
        caches: dict[int, "collections.OrderedDict"] = {}
        locks: dict[int, asyncio.Lock] = {}

        @functools.wraps(load_fn)
        async def wrapper(*args):
            # args = (self, model_id) for methods, (model_id,) for functions
            key = id(args[0]) if len(args) > 1 else 0
            model_id = args[-1]
            cache = caches.setdefault(key, collections.OrderedDict())
            lock = locks.setdefault(key, asyncio.Lock())
            async with lock:
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = load_fn(*args)
                if inspect.iscoroutine(model):
                    model = await model
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    _, evicted = cache.popitem(last=False)
                    unload = getattr(evicted, "unload", None) or getattr(
                        evicted, "__serve_unload__", None
                    )
                    if unload is not None:
                        result = unload()
                        if inspect.iscoroutine(result):
                            await result
                return model

        return wrapper

    if _fn is not None:
        return decorator(_fn)
    return decorator

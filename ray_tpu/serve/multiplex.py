"""@serve.multiplexed — per-replica LRU of loaded models.

Role-equivalent of python/ray/serve/multiplex.py (SURVEY §2.6): a replica
lazily loads up to N models keyed by the request's multiplexed_model_id;
least-recently-used models are evicted (calling their __del__/unload). The
router steers by model id when possible via DeploymentHandle.options(
multiplexed_model_id=...).
"""

from __future__ import annotations

import asyncio
import collections
import functools
import inspect
from typing import Callable

from ray_tpu.serve._private.replica import get_current_request_metadata


def get_multiplexed_model_id() -> str:
    meta = get_current_request_metadata()
    if meta is None:
        return ""
    return meta.get("multiplexed_model_id", "")


# Every @multiplexed decorator's cache map, so a draining replica can
# checkpoint its loaded models before the process exits (ISSUE 13).
_ALL_CACHES: list = []

# Active-use pins (ISSUE 17 satellite 6): a model serving a live token
# stream or holding decode-engine slots must not be LRU-evicted mid-
# stream — its KV state and checkpoint would race the stream's writes.
# Pinned models survive the eviction scan; the eviction they dodged is
# recorded and replayed when the last pin releases, so the cache still
# converges to its LRU bound.
_PINS: dict[str, int] = {}
_DEFERRED: list = []  # (cache, max_models) pairs still over budget


def pin_model(model_id: str) -> None:
    """Mark a multiplexed model as in active use (stream open / engine
    slots resident). Idempotent across concurrent streams — each pin
    needs a matching :func:`unpin_model`."""
    if model_id:
        _PINS[model_id] = _PINS.get(model_id, 0) + 1


def unpin_model(model_id: str) -> None:
    """Release one pin; when the last pin on the last over-budget model
    drops, any eviction deferred by :func:`pin_model` runs (checkpoint-
    then-unload, same as a live eviction)."""
    if not model_id:
        return
    remaining = _PINS.get(model_id, 0) - 1
    if remaining > 0:
        _PINS[model_id] = remaining
        return
    _PINS.pop(model_id, None)
    if _DEFERRED:
        _schedule_deferred_evictions()


def pinned_models() -> dict[str, int]:
    """Snapshot of model_id -> pin count (test/debug surface)."""
    return dict(_PINS)


async def _checkpoint_evict(cache, max_models: int,
                            protect: frozenset = frozenset()) -> None:
    """Evict LRU-first down to ``max_models``, skipping pinned models
    and ``protect`` (the model being loaded right now — it is about to
    be handed to the caller). Eviction is checkpoint-then-unload: the
    model's state is durable before its memory is released. If pins
    keep the cache over budget, the remainder is deferred to the next
    unpin."""
    import logging

    for model_id in list(cache.keys()):
        if len(cache) <= max_models:
            break
        if _PINS.get(model_id) or model_id in protect:
            continue
        model = cache.pop(model_id)
        for hook_name in ("checkpoint", "__serve_checkpoint__"):
            hook = getattr(model, hook_name, None)
            if hook is not None:
                try:
                    result = hook()
                    if inspect.iscoroutine(result):
                        await result
                except Exception as exc:
                    logging.getLogger(__name__).warning(
                        "checkpoint of evicted model %r failed: %s",
                        model_id, exc,
                    )
                break
        unload = getattr(model, "unload", None) or getattr(
            model, "__serve_unload__", None
        )
        if unload is not None:
            result = unload()
            if inspect.iscoroutine(result):
                await result
    if len(cache) > max_models:
        entry = (cache, max_models)
        if entry not in [(c, m) for c, m in _DEFERRED]:
            _DEFERRED.append(entry)


async def _drain_deferred_evictions() -> None:
    pending, _DEFERRED[:] = list(_DEFERRED), []
    for cache, max_models in pending:
        await _checkpoint_evict(cache, max_models)


def _schedule_deferred_evictions() -> None:
    try:
        asyncio.get_running_loop().create_task(_drain_deferred_evictions())
    except RuntimeError:
        # No running loop (sync unpin path, e.g. tests): drain inline.
        asyncio.run(_drain_deferred_evictions())


async def checkpoint_loaded_models() -> int:
    """Call ``checkpoint``/``__serve_checkpoint__`` on every model loaded
    through @multiplexed in this process. Returns how many models were
    checkpointed; per-model failures are logged and skipped (a drain must
    not wedge on one broken model)."""
    import logging

    count = 0
    for caches in _ALL_CACHES:
        for cache in caches.values():
            for model_id, model in list(cache.items()):
                hook = getattr(model, "checkpoint", None) or getattr(
                    model, "__serve_checkpoint__", None
                )
                if hook is None:
                    continue
                try:
                    result = hook()
                    if inspect.iscoroutine(result):
                        await result
                    count += 1
                except Exception as exc:
                    logging.getLogger(__name__).warning(
                        "checkpoint of multiplexed model %r failed: %s",
                        model_id, exc,
                    )
    return count


def multiplexed(
    _fn: Callable | None = None, *, max_num_models_per_replica: int = 3
):
    """Decorator on `async def load(self, model_id) -> model`."""

    def decorator(load_fn: Callable):
        caches: dict[int, "collections.OrderedDict"] = {}
        locks: dict[int, asyncio.Lock] = {}
        _ALL_CACHES.append(caches)

        @functools.wraps(load_fn)
        async def wrapper(*args):
            # args = (self, model_id) for methods, (model_id,) for functions
            key = id(args[0]) if len(args) > 1 else 0
            model_id = args[-1]
            cache = caches.setdefault(key, collections.OrderedDict())
            lock = locks.setdefault(key, asyncio.Lock())
            async with lock:
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = load_fn(*args)
                if inspect.iscoroutine(model):
                    model = await model
                cache[model_id] = model
                await _checkpoint_evict(
                    cache, max_num_models_per_replica,
                    protect=frozenset((model_id,)),
                )
                return model

        return wrapper

    if _fn is not None:
        return decorator(_fn)
    return decorator

"""@serve.batch — dynamic request batching.

Role-equivalent of python/ray/serve/batching.py :: @serve.batch
(max_batch_size, batch_wait_timeout_s), with the TPU-first addition from
SURVEY §2.6/§7.0.5: optional `bucket_sizes` — the flushed batch is padded
up to the nearest bucket by repeating the last item, so a jitted XLA model
sees only a fixed set of batch shapes (one compile per bucket, no
recompile storms). The wrapper returns per-item results with padding
stripped.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
import threading
import weakref
from typing import Any, Callable, Optional, Sequence

from ray_tpu import exceptions
from ray_tpu.serve._private.common import Deadline, current_deadline

# Shape keys this PROCESS has compiled for (one replica per process):
# bucket flushes land here; the replica wrapper unions them into its
# warm-shape report for compile-cache-aware routing (SURVEY §3.4).
_WARM_SHAPES: set[str] = set()
_WARM_LOCK = threading.Lock()

# Live batch queues in this process (ISSUE 8): weak refs so a replica
# teardown doesn't leak queues, read by queue_stats() for the replica's
# occupancy/queue-depth gauges.
_QUEUES: "weakref.WeakSet[_BatchQueue]" = weakref.WeakSet()


def queue_stats() -> dict:
    """Aggregate batching stats across this process's live queues.

    ``queue_depth`` is the number of requests waiting for a flush right
    now; ``batch_occupancy`` is real/padded items of the last flushed
    batch (1.0 when bucket padding is off), ``avg_occupancy`` the
    lifetime ratio. A padded TPU batch at 0.3 occupancy means 70% of the
    XLA step fed duplicated filler — the serve-side analogue of a
    data-bound train step."""
    depth = 0
    batches = 0
    real = 0
    padded = 0
    last_occ = None
    for queue in list(_QUEUES):
        depth += len(queue.queue)
        batches += queue.batches
        real += queue.items_real
        padded += queue.items_padded
        if queue.last_occupancy is not None:
            last_occ = (
                queue.last_occupancy if last_occ is None
                else min(last_occ, queue.last_occupancy)
            )
    return {
        "queue_depth": depth,
        "batches": batches,
        "items_real": real,
        "items_padded": padded,
        "avg_occupancy": (real / padded) if padded else None,
        "batch_occupancy": last_occ,
    }


def note_warm_shape(key: str) -> None:
    with _WARM_LOCK:
        _WARM_SHAPES.add(key)


def warm_shapes() -> set[str]:
    with _WARM_LOCK:
        return set(_WARM_SHAPES)


class _BatchQueue:
    def __init__(
        self,
        fn: Callable,
        max_batch_size: int,
        batch_wait_timeout_s: float,
        bucket_sizes: Optional[Sequence[int]],
    ):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.bucket_sizes = sorted(bucket_sizes) if bucket_sizes else None
        if self.bucket_sizes and self.bucket_sizes[-1] < max_batch_size:
            raise ValueError(
                "largest bucket must be >= max_batch_size "
                f"({self.bucket_sizes[-1]} < {max_batch_size})"
            )
        # (item, future, deadline) — the request's propagated Deadline
        # rides along so a flush can expire entries that waited past
        # their budget instead of feeding dead work to the model.
        self.queue: list[tuple[Any, asyncio.Future, Optional[Deadline]]] = []
        self._flusher: asyncio.Task | None = None
        self._lock = asyncio.Lock()
        # Flight-recorder counters (ISSUE 8): read by queue_stats().
        self.batches = 0
        self.items_real = 0
        self.items_padded = 0
        self.last_occupancy: float | None = None
        _QUEUES.add(self)

    def _pad(self, items: list) -> tuple[list, int]:
        real = len(items)
        if self.bucket_sizes:
            bucket = next(
                (b for b in self.bucket_sizes if b >= real), self.bucket_sizes[-1]
            )
            items = items + [items[-1]] * (bucket - real)
            # This process's jitted model has now compiled (or is about
            # to compile) this bucket shape: report it warm so routers
            # can steer same-shape traffic here (SURVEY §3.4 TPU note).
            note_warm_shape(f"batch:{bucket}")
        return items, real

    async def submit(self, item: Any) -> Any:
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        async with self._lock:
            self.queue.append((item, future, current_deadline()))
            if len(self.queue) >= self.max_batch_size:
                self._take_and_flush()
            elif self._flusher is None or self._flusher.done():
                self._flusher = asyncio.get_running_loop().create_task(
                    self._flush_after_timeout()
                )
        return await future

    def _take_and_flush(self) -> None:
        batch = self.queue[: self.max_batch_size]
        del self.queue[: self.max_batch_size]
        asyncio.get_running_loop().create_task(self._run_batch(batch))

    async def _flush_after_timeout(self) -> None:
        await asyncio.sleep(self.batch_wait_timeout_s)
        async with self._lock:
            if self.queue:
                self._take_and_flush()

    async def _run_batch(self, batch: list) -> None:
        # Expire entries whose deadline lapsed while queued: feeding them
        # to the model wastes a padded-batch slot on an answer nobody is
        # waiting for (the caller already saw DeadlineExceededError).
        fresh = []
        for item, future, deadline in batch:
            if deadline is not None and deadline.expired():
                if not future.done():
                    future.set_exception(
                        exceptions.DeadlineExceededError(
                            "request expired while queued for batching"
                        )
                    )
            else:
                fresh.append((item, future))
        if not fresh:
            return
        items = [item for item, _ in fresh]
        futures = [future for _, future in fresh]
        padded, real = self._pad(items)
        self.batches += 1
        self.items_real += real
        self.items_padded += len(padded)
        self.last_occupancy = real / len(padded) if padded else None
        try:
            result = self.fn(padded)
            if inspect.iscoroutine(result):
                result = await result
            results = list(result)[:real]
            if len(results) != real:
                raise ValueError(
                    f"batched fn returned {len(results)} results for "
                    f"{real} requests"
                )
            for future, value in zip(futures, results):
                if not future.done():
                    future.set_result(value)
        except Exception as exc:
            for future in futures:
                if not future.done():
                    future.set_exception(exc)


def batch(
    _fn: Callable | None = None,
    *,
    max_batch_size: int = 10,
    batch_wait_timeout_s: float = 0.01,
    bucket_sizes: Optional[Sequence[int]] = None,
):
    """Decorator: async def fn(self, items: list) -> list, called per item."""

    def decorator(fn: Callable):
        queues: dict[int, _BatchQueue] = {}

        def _queue_for(bound_args: tuple) -> _BatchQueue:
            # One queue per bound instance (methods) / per function.
            key = id(bound_args[0]) if bound_args else 0
            if key not in queues:
                if bound_args:
                    target = functools.partial(fn, bound_args[0])
                else:
                    target = fn
                queues[key] = _BatchQueue(
                    target, max_batch_size, batch_wait_timeout_s, bucket_sizes
                )
            return queues[key]

        is_method = "self" in inspect.signature(fn).parameters

        if is_method:
            @functools.wraps(fn)
            async def method_wrapper(self, item):
                return await _queue_for((self,)).submit(item)

            return method_wrapper

        @functools.wraps(fn)
        async def fn_wrapper(item):
            return await _queue_for(()).submit(item)

        return fn_wrapper

    if _fn is not None:
        return decorator(_fn)
    return decorator

"""ServeController — the singleton reconciliation actor.

Role-equivalent of python/ray/serve/_private/controller.py ::
ServeController + deployment_state.py :: DeploymentStateManager +
application_state.py (SURVEY §2.6, §3.4): holds target state (apps →
deployments), runs a reconcile loop that starts/stops replica actors to
match target counts, health-checks replicas, applies rolling updates on
version change, autoscales from replica queue metrics, and checkpoints
target state to the controller KV [N6] so a restarted controller replays
the reconcile.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading
import time
import traceback
import uuid
from typing import Any, Optional

import ray_tpu
from ray_tpu.serve._private.autoscaling_policy import AutoscalingState
from ray_tpu.serve._private.common import (
    DeploymentConfig,
    DeploymentInfo,
    ReplicaInfo,
    new_replica_id,
)
from ray_tpu.serve._private.replica import Replica

RECONCILE_PERIOD_S = 0.25
# Proxy liveness + route-p99 + oom_risk scans ride a slower tick than the
# reconcile loop: each is an RPC or a file read, not a dict diff.
PROXY_CHECK_PERIOD_S = 1.0

logger = logging.getLogger(__name__)


def _inc_reliability(name: str, **tags) -> None:
    """Best-effort reliability counter bump (metric export must never take
    down the reconcile loop)."""
    try:
        from ray_tpu.util import metrics as metrics_mod

        metrics_mod.inc_serve_reliability(name, **tags)
    except Exception:  # rtlint: disable=swallowed-exception - metrics backend unavailable; reconcile continues
        pass


def _kv_call(method: str, payload: dict) -> Any:
    from ray_tpu._private import worker as worker_mod

    ctx = worker_mod.get_global_context()
    return ctx.io.run(ctx.controller.call(method, payload))


class ServeController:
    """Hosted in a detached named actor (max_concurrency > 1)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._deployments: dict[str, DeploymentInfo] = {}  # qualified name →
        self._replicas: dict[str, list[ReplicaInfo]] = {}
        self._actor_handles: dict[str, Any] = {}
        self._autoscalers: dict[str, AutoscalingState] = {}
        self._autoscale_counts: dict[str, int] = {}
        self._routes: dict[str, str] = {}  # route_prefix → qualified name
        self._app_deployments: dict[str, list[str]] = {}
        self._app_status: dict[str, str] = {}
        self._applied_user_config: dict[str, Any] = {}
        self._stopped = False
        # Long-poll push state (reference: _private/long_poll.py host):
        # proxies/routers block in poll_update() until the membership
        # version advances instead of polling get_routes every second.
        self._config_version = 0
        self._config_cond = threading.Condition(self._lock)
        self._last_snapshot: dict | None = None
        self._pollers: set = set()  # (loop, asyncio.Event) of parked polls
        # Instance token: a restarted controller restarts versions at 0;
        # subscribers detect the epoch change and resync from scratch.
        self._instance = uuid.uuid4().hex
        # Keyed by qualified deployment name: a single controller-wide
        # timestamp would let the first deployment in iteration order
        # starve every other deployment's health checks.
        self._last_health_check: dict = {}
        # Ingress proxy registry (ISSUE 13): name → {"name", "protocol",
        # "host", "port"}. The reconcile loop health-checks each one and
        # restarts it under the same name/port on death; the set is
        # published in the membership snapshot so clients can fail over.
        self._proxies: dict[str, dict] = {}
        self._last_proxy_check = 0.0
        # Latest per-route p99 (ms) scraped from proxy SLO histograms,
        # fed into the autoscaler beside queue depth.
        self._route_p99: dict[str, float] = {}
        # oom_risk event high-water mark (the jax_trainer consumer
        # pattern): only events newer than this trigger drains.
        self._oom_seen = 0
        self._restore_checkpoint()
        self._thread = threading.Thread(target=self._reconcile_loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    # target-state API (called by serve.run / CLI)
    # ------------------------------------------------------------------
    def deploy_application(
        self, app_name: str, deployments: list[dict], route_prefix: Optional[str]
    ) -> str:
        with self._lock:
            new_names = []
            for spec in deployments:
                info = DeploymentInfo(
                    name=spec["name"],
                    app_name=app_name,
                    config=spec["config"],
                    cls_or_fn=spec["cls_or_fn"],
                    init_args=spec.get("init_args", ()),
                    init_kwargs=spec.get("init_kwargs", {}),
                    version=spec.get("version") or self._version_of(spec),
                    route_prefix=spec.get("route_prefix"),
                )
                qname = info.qualified_name()
                new_names.append(qname)
                self._deployments[qname] = info
                self._replicas.setdefault(qname, [])
                if info.config.autoscaling_config:
                    self._autoscalers[qname] = AutoscalingState(
                        info.config.autoscaling_config
                    )
                    self._autoscale_counts.setdefault(
                        qname, info.config.autoscaling_config.min_replicas
                    )
                # user_config change → in-place reconfigure of live replicas
                prev = self._applied_user_config.get(qname, object())
                if prev != info.config.user_config:
                    self._applied_user_config[qname] = info.config.user_config
                    for rep in self._replicas.get(qname, []):
                        actor = self._actor_handles.get(rep.actor_name)
                        if actor is not None and rep.state == "RUNNING":
                            try:
                                actor.reconfigure.remote(info.config.user_config)
                            except Exception:
                                # Replica death is handled by the health
                                # check; the new config lands on its
                                # replacement.
                                logger.debug(
                                    "reconfigure push to %s failed",
                                    rep.actor_name, exc_info=True,
                                )
            # Remove deployments dropped from the app.
            for qname in self._app_deployments.get(app_name, []):
                if qname not in new_names:
                    self._deployments.pop(qname, None)
                    self._last_health_check.pop(qname, None)
            self._app_deployments[app_name] = new_names
            self._app_status[app_name] = "DEPLOYING"
            if route_prefix is not None and deployments:
                ingress = deployments[-1]
                self._routes[route_prefix] = f"{app_name}_{ingress['name']}"
            self._bump_version_locked()
        self._save_checkpoint()
        return "ok"

    def delete_application(self, app_name: str) -> str:
        with self._lock:
            for qname in self._app_deployments.pop(app_name, []):
                self._deployments.pop(qname, None)
                self._last_health_check.pop(qname, None)
            self._routes = {
                r: d for r, d in self._routes.items()
                if not d.startswith(app_name + "_")
            }
            self._app_status.pop(app_name, None)
            self._bump_version_locked()
        self._save_checkpoint()
        return "ok"

    def shutdown(self) -> str:
        with self._lock:
            self._deployments.clear()
            self._routes.clear()
            self._app_deployments.clear()
            self._last_health_check.clear()
        # reconcile loop will drain replicas; mark stop after one pass
        time.sleep(2 * RECONCILE_PERIOD_S)
        self._stopped = True
        # Wake parked poll_update subscribers so they observe the stop now
        # instead of riding out their full long-poll timeout.
        self._notify_pollers()
        return "ok"

    # ------------------------------------------------------------------
    # introspection (routers, proxies, serve.status)
    # ------------------------------------------------------------------
    def get_deployment_replicas(self, qualified_name: str) -> dict:
        with self._lock:
            info = self._deployments.get(qualified_name)
            running = [
                r.actor_name
                for r in self._replicas.get(qualified_name, [])
                if r.state == "RUNNING"
            ]
            return {
                "actor_names": running,
                "max_ongoing_requests": (
                    info.config.max_ongoing_requests if info else 100
                ),
            }

    def get_routes(self) -> dict:
        with self._lock:
            return dict(self._routes)

    # ------------------------------------------------------------------
    # ingress proxy lifecycle (ISSUE 13)
    # ------------------------------------------------------------------
    def register_proxy(
        self, name: str, protocol: str, host: str, port: int
    ) -> str:
        """serve.start() reports each proxy it launched; from then on the
        controller owns its liveness (health-check + restart on death)."""
        with self._lock:
            self._proxies[name] = {
                "name": name, "protocol": protocol,
                "host": host, "port": int(port),
            }
            self._bump_version_locked()
        return "ok"

    def unregister_proxy(self, name: str) -> str:
        with self._lock:
            self._proxies.pop(name, None)
            self._bump_version_locked()
        return "ok"

    def get_proxies(self) -> list:
        with self._lock:
            return [dict(p) for p in self._proxies.values()]

    def _ensure_proxies(self) -> None:
        """Health-check every registered proxy; restart the dead ones under
        the same name/port so clients that pinned an address recover."""
        with self._lock:
            descriptors = [dict(p) for p in self._proxies.values()]
        for desc in descriptors:
            name = desc["name"]
            try:
                handle = ray_tpu.get_actor(name)
                ray_tpu.get(handle.get_num_requests.remote(), timeout=5)
                continue
            except Exception:  # rtlint: disable=swallowed-exception - dead/unreachable proxy detected; restart path follows
                pass
            logger.warning("proxy %s is down; restarting", name)
            try:
                if desc["protocol"] == "grpc":
                    from ray_tpu.serve._private.grpc_proxy import GRPCProxy

                    proxy_cls: Any = GRPCProxy
                else:
                    from ray_tpu.serve._private.proxy import HTTPProxy

                    proxy_cls = HTTPProxy
                ray_tpu.remote(proxy_cls).options(
                    name=name, lifetime="detached", max_concurrency=64
                ).remote(desc["host"], desc["port"])
                _inc_reliability("proxy_restarts", proxy=name)
            except Exception:
                # Name may still be registered while the old actor's death
                # propagates; the next tick retries.
                logger.warning("proxy %s restart failed", name, exc_info=True)

    def _scrape_route_p99(self) -> None:
        """Pull per-route p99 from each HTTP proxy's SLO histograms (ISSUE
        8) for the autoscaler; routes served by several proxies report the
        worst tail."""
        with self._lock:
            descriptors = [
                dict(p) for p in self._proxies.values()
                if p["protocol"] == "http"
            ]
        merged: dict[str, float] = {}
        for desc in descriptors:
            try:
                handle = ray_tpu.get_actor(desc["name"])
                stats = ray_tpu.get(handle.get_route_stats.remote(), timeout=5)
            except Exception:  # rtlint: disable=swallowed-exception - proxy down; _ensure_proxies handles it
                continue
            for route, snap in stats.items():
                p99 = snap.get("p99_ms")
                if p99 is not None:
                    merged[route] = max(merged.get(route, 0.0), p99)
        if merged:
            self._route_p99.update(merged)

    # ------------------------------------------------------------------
    # long-poll push (reference: long_poll.py LongPollHost)
    # ------------------------------------------------------------------
    def _bump_version_locked(self) -> None:
        self._config_version += 1
        self._last_snapshot = None  # recompute lazily at next poll
        self._notify_pollers()

    def _notify_pollers(self) -> None:
        """Wake every parked poll_update coroutine (they wait on per-call
        asyncio.Events; version bumps come from controller threads, so the
        wake crosses into each poller's loop threadsafely)."""
        for loop, event in list(self._pollers):
            try:
                loop.call_soon_threadsafe(event.set)
            except Exception:  # rtlint: disable=swallowed-exception - poller loop may be closed; next poll re-registers
                pass

    def _membership_snapshot(self) -> dict:
        with self._lock:
            replicas = {}
            for qname, info in self._deployments.items():
                running = sorted(
                    r.actor_name
                    for r in self._replicas.get(qname, [])
                    if r.state == "RUNNING"
                )
                replicas[qname] = {
                    "actor_names": running,
                    "max_ongoing_requests": info.config.max_ongoing_requests,
                    # Reliability policy (ISSUE 13): routers/proxies price
                    # deadlines, retries, and admission from deployment
                    # config instead of hardcoded constants.
                    "policy": info.config.policy_snapshot(),
                }
            return {
                "routes": dict(self._routes),
                "replicas": replicas,
                "proxies": [dict(p) for p in self._proxies.values()],
            }

    def _publish_if_changed(self) -> None:
        """End of each reconcile pass: if membership changed (replica went
        RUNNING/DEAD, routes changed), advance the version and wake every
        blocked poll_update."""
        snapshot = self._membership_snapshot()
        with self._config_cond:
            if snapshot != self._last_snapshot:
                self._config_version += 1
                self._last_snapshot = snapshot
                self._notify_pollers()

    async def poll_update(
        self, last_version: int = -1, timeout_s: float = 10.0
    ) -> dict:
        """Block until the membership version advances past last_version
        (or timeout); returns the fresh snapshot. Proxies and routers call
        this in a loop — push semantics over an actor call. async so each
        blocked subscriber is a coroutine on the actor's async lane, NOT a
        pinned concurrency slot (N subscribers would otherwise starve the
        control plane)."""
        import asyncio

        loop = asyncio.get_running_loop()
        event = asyncio.Event()
        entry = (loop, event)
        with self._lock:
            ready = self._config_version > last_version or self._stopped
            if not ready:
                self._pollers.add(entry)
        if not ready:
            try:
                await asyncio.wait_for(event.wait(), timeout_s)
            except asyncio.TimeoutError:
                pass
            finally:
                with self._lock:
                    self._pollers.discard(entry)
        with self._config_cond:
            snapshot = self._last_snapshot
            if snapshot is None:
                snapshot = self._membership_snapshot()
                self._last_snapshot = snapshot
            return {
                "version": self._config_version,
                "instance": self._instance,
                **snapshot,
            }

    def get_status(self) -> dict:
        with self._lock:
            apps = {}
            for app, qnames in self._app_deployments.items():
                deployments = {}
                for qname in qnames:
                    reps = self._replicas.get(qname, [])
                    info = self._deployments.get(qname)
                    target = self._target_count(qname, info) if info else 0
                    running = sum(1 for r in reps if r.state == "RUNNING")
                    deployments[qname.split("_", 1)[1]] = {
                        "target_replicas": target,
                        "running_replicas": running,
                        "states": [r.state for r in reps],
                    }
                all_ok = all(
                    d["running_replicas"] >= d["target_replicas"]
                    for d in deployments.values()
                )
                apps[app] = {
                    "status": "RUNNING" if all_ok else self._app_status.get(app, "DEPLOYING"),
                    "deployments": deployments,
                }
            return apps

    def get_metrics(self) -> dict:
        out = {}
        with self._lock:
            replicas = {
                q: [r for r in reps if r.state == "RUNNING"]
                for q, reps in self._replicas.items()
            }
        for qname, reps in replicas.items():
            metrics = []
            for rep in reps:
                try:
                    handle = self._actor_handles.get(rep.actor_name)
                    if handle:
                        metrics.append(
                            ray_tpu.get(handle.get_metrics.remote(), timeout=5)
                        )
                except Exception:  # rtlint: disable=swallowed-exception - metrics fetch from a dying replica; skip it
                    pass
            out[qname] = metrics
        return out

    def ping(self) -> str:
        return "ok"

    # ------------------------------------------------------------------
    # reconcile loop
    # ------------------------------------------------------------------
    def _reconcile_loop(self) -> None:
        while not self._stopped:
            try:
                self._reconcile_once()
            except Exception:
                traceback.print_exc()
            time.sleep(RECONCILE_PERIOD_S)

    def _target_count(self, qname: str, info: DeploymentInfo) -> int:
        if info.config.autoscaling_config:
            return self._autoscale_counts.get(
                qname, info.config.autoscaling_config.min_replicas
            )
        return info.config.num_replicas

    def _reconcile_once(self) -> None:
        with self._lock:
            targets = dict(self._deployments)
        # Slow tick: proxy liveness, route-p99 scrape, oom_risk scan (each
        # is an RPC or a file read — too heavy for every 0.25s pass).
        now = time.monotonic()
        if now - self._last_proxy_check >= PROXY_CHECK_PERIOD_S:
            self._last_proxy_check = now
            self._ensure_proxies()
            self._scrape_route_p99()
            self._drain_oom_flagged()
        # Drain replicas of deleted deployments.
        for qname in list(self._replicas):
            if qname not in targets:
                for rep in self._replicas.get(qname, []):
                    self._stop_replica(rep, trigger="app_delete")
                with self._lock:
                    self._replicas.pop(qname, None)
        for qname, info in targets.items():
            self._autoscale(qname, info)
            target = self._target_count(qname, info)
            replicas = self._replicas.setdefault(qname, [])
            # Rolling update: stop replicas of stale versions first.
            stale = [r for r in replicas if r.version != info.version]
            for rep in stale:
                self._stop_replica(
                    rep,
                    timeout_s=info.config.graceful_shutdown_timeout_s,
                    trigger="rolling_update",
                )
                replicas.remove(rep)
            alive = [r for r in replicas if r.state in ("STARTING", "RUNNING")]
            for _ in range(target - len(alive)):
                rep = self._start_replica(qname, info)
                if rep is not None:
                    replicas.append(rep)
            excess = len(alive) - target
            if excess > 0:
                # Scale-down prefers drains over kills: the replica leaves
                # the routing set first, finishes in-flight work, then dies.
                for rep in alive[-excess:]:
                    self._stop_replica(
                        rep,
                        timeout_s=info.config.graceful_shutdown_timeout_s,
                        trigger="scale_down",
                    )
                    replicas.remove(rep)
            self._health_check(qname, info, replicas)
        self._publish_if_changed()

    def _start_replica(self, qname: str, info: DeploymentInfo) -> ReplicaInfo | None:
        replica_id = new_replica_id(qname)
        actor_name = f"SERVE_REPLICA::{replica_id}"
        options = dict(
            name=actor_name,
            max_concurrency=max(8, info.config.max_ongoing_requests),
            num_cpus=info.config.ray_actor_options.get("num_cpus", 1),
        )
        if info.config.ray_actor_options.get("num_tpus"):
            options["num_tpus"] = info.config.ray_actor_options["num_tpus"]
        if info.config.ray_actor_options.get("resources"):
            options["resources"] = info.config.ray_actor_options["resources"]
        try:
            actor = ray_tpu.remote(Replica).options(**options).remote(
                replica_id,
                qname,
                info.cls_or_fn,
                info.init_args,
                info.init_kwargs,
                info.config.user_config,
                info.version,
                # Admission + drain knobs the replica enforces locally.
                limits=info.config.policy_snapshot(),
            )
        except Exception:
            traceback.print_exc()
            return None
        self._actor_handles[actor_name] = actor
        rep = ReplicaInfo(
            replica_id=replica_id,
            deployment=qname,
            actor_name=actor_name,
            state="STARTING",
            version=info.version,
        )
        # Async readiness probe: mark RUNNING when first health check lands.
        threading.Thread(
            target=self._await_ready, args=(rep, actor), daemon=True
        ).start()
        return rep

    def _await_ready(self, rep: ReplicaInfo, actor) -> None:
        try:
            ray_tpu.get(actor.check_health.remote(), timeout=120)
            try:
                rep.node_id = ray_tpu.get(
                    actor.get_node_id.remote(), timeout=10
                )
            except Exception:  # rtlint: disable=swallowed-exception - node id is only used for oom_risk targeting
                pass
            rep.state = "RUNNING"
        except Exception:
            rep.state = "DEAD"

    def _stop_replica(
        self,
        rep: ReplicaInfo,
        timeout_s: float = 20.0,
        trigger: str = "scale_down",
    ) -> None:
        """Drain-before-kill (ISSUE 13): flip the replica to DRAINING (the
        membership publish pulls it from every router), let in-flight
        requests finish up to the graceful timeout, then kill. The replica
        checkpoints its multiplexed models inside drain()."""
        rep.state = "DRAINING"
        actor = self._actor_handles.pop(rep.actor_name, None)
        if actor is None:
            rep.state = "DEAD"
            return
        _inc_reliability("drains", deployment=rep.deployment, trigger=trigger)

        def _drain():
            try:
                ray_tpu.get(actor.drain.remote(), timeout=10)
            except Exception:  # rtlint: disable=swallowed-exception - replica hung entering drain; the kill below still lands
                pass
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                try:
                    ongoing = ray_tpu.get(
                        actor.get_num_ongoing.remote(), timeout=5
                    )
                except Exception:  # rtlint: disable=swallowed-exception - replica died mid-drain; nothing left to wait for
                    break
                if ongoing <= 0:
                    break
                time.sleep(0.25)
            try:
                ray_tpu.kill(actor)
            except Exception:  # rtlint: disable=swallowed-exception - actor already dead
                pass
            rep.state = "DEAD"

        threading.Thread(target=_drain, daemon=True).start()

    def _drain_oom_flagged(self) -> None:
        """Proactive drain on oom_risk telemetry (ISSUE 5 → ISSUE 13): the
        node agent projects a worker past its memory limit and publishes an
        oom_risk event; replicas on that node drain (checkpointing loaded
        models) before the OOM killer takes them mid-request. The reconcile
        pass starts replacements as soon as the drain drops them from the
        alive set."""
        session_dir = os.environ.get("RAYTPU_SESSION_DIR")
        if not session_dir:
            try:
                session_dir = ray_tpu.runtime_info().get("session_dir")
            except Exception:  # rtlint: disable=swallowed-exception - no cluster context: no events to read
                return
        if not session_dir:
            return
        try:
            from ray_tpu._private.event_export import read_events

            events = read_events(session_dir, "oom_risk")
        except Exception:  # rtlint: disable=swallowed-exception - unreadable events dir; retry next tick
            return
        fresh = events[self._oom_seen:]
        if not fresh:
            return
        self._oom_seen = len(events)
        nodes = {
            ev.get("data", {}).get("node_id") for ev in fresh
        } - {None, ""}
        if not nodes:
            return
        with self._lock:
            deployments = dict(self._deployments)
        for qname, info in deployments.items():
            replicas = self._replicas.get(qname, [])
            flagged = [
                r for r in replicas
                if r.state == "RUNNING" and r.node_id in nodes
            ]
            for rep in flagged:
                logger.warning(
                    "draining replica %s: oom_risk on node %s",
                    rep.replica_id, rep.node_id,
                )
                # Stay in the replicas list as DRAINING: the alive count
                # drops, so the same pass starts a replacement elsewhere.
                self._stop_replica(
                    rep,
                    timeout_s=info.config.graceful_shutdown_timeout_s,
                    trigger="oom_risk",
                )

    def _health_check(self, qname, info, replicas: list[ReplicaInfo]) -> None:
        now = time.monotonic()
        last = self._last_health_check.get(qname, 0.0)
        if now - last < info.config.health_check_period_s:
            return
        self._last_health_check[qname] = now
        for rep in [r for r in replicas if r.state == "RUNNING"]:
            actor = self._actor_handles.get(rep.actor_name)
            if actor is None:
                rep.state = "DEAD"
                continue
            try:
                result = ray_tpu.get(
                    actor.check_health.remote(),
                    timeout=info.config.health_check_timeout_s,
                )
            except Exception:
                rep.state = "DEAD"
                self._actor_handles.pop(rep.actor_name, None)
                try:
                    ray_tpu.kill(actor)
                except Exception:  # rtlint: disable=swallowed-exception - kill of an already-dead replica
                    pass
                continue
            if result == "draining":
                # The replica started draining on its own (SIGTERM from
                # the platform): honor it — pull it from routing, let
                # in-flight work finish, and let reconcile start a
                # replacement. _stop_replica's drain() call is idempotent.
                self._stop_replica(
                    rep,
                    timeout_s=info.config.graceful_shutdown_timeout_s,
                    trigger="sigterm",
                )
        self._replicas[qname] = [r for r in replicas if r.state != "DEAD"]

    def _autoscale(self, qname: str, info: DeploymentInfo) -> None:
        state = self._autoscalers.get(qname)
        if state is None:
            return
        running = [
            r for r in self._replicas.get(qname, []) if r.state == "RUNNING"
        ]
        total_ongoing = 0.0
        queue_depth = 0.0
        kv_free_frac: float | None = None
        for rep in running:
            actor = self._actor_handles.get(rep.actor_name)
            if actor is None:
                continue
            try:
                load = ray_tpu.get(actor.get_load.remote(), timeout=5)
                total_ongoing += load.get("ongoing", 0)
                queue_depth += load.get("queue_depth", 0)
                # Decode replicas report paged-KV headroom (ISSUE 17);
                # the pool scales on its WORST replica — one full pool
                # stalls that replica's admission even if siblings idle.
                frac = load.get("kv_free_frac")
                if frac is not None:
                    kv_free_frac = (
                        frac if kv_free_frac is None
                        else min(kv_free_frac, frac)
                    )
            except Exception:  # rtlint: disable=swallowed-exception - queue-depth probe failed; autoscale on what we have
                pass
        current = self._autoscale_counts.get(
            qname, info.config.autoscaling_config.min_replicas
        )
        # SLO input (ISSUE 13): the proxies' per-route p99 (scraped on the
        # slow tick) turns tail-latency breaches into upscale pressure.
        decision = state.decide(
            total_ongoing,
            current,
            queue_depth=queue_depth,
            p99_ms=self._route_p99.get(qname),
            kv_free_frac=kv_free_frac,
        )
        if decision != current:
            self._autoscale_counts[qname] = decision

    # ------------------------------------------------------------------
    # checkpoint/recovery via controller KV [N6]
    # ------------------------------------------------------------------
    def _save_checkpoint(self) -> None:
        with self._lock:
            state = {
                "deployments": self._deployments,
                "routes": self._routes,
                "app_deployments": self._app_deployments,
            }
        try:
            _kv_call(
                "kv_put",
                {
                    "namespace": "serve",
                    "key": "controller_checkpoint",
                    "value": pickle.dumps(state),
                    "overwrite": True,
                },
            )
        except Exception:
            # A lost checkpoint only bites on controller restart — which is
            # exactly when nobody is watching. Make the gap visible now.
            logger.warning("controller checkpoint save failed", exc_info=True)

    def _restore_checkpoint(self) -> None:
        try:
            resp = _kv_call(
                "kv_get", {"namespace": "serve", "key": "controller_checkpoint"}
            )
            if resp.get("status") == "ok" and resp.get("value"):
                state = pickle.loads(resp["value"])
                self._deployments = state["deployments"]
                self._routes = state["routes"]
                self._app_deployments = state["app_deployments"]
                for qname, info in self._deployments.items():
                    self._replicas.setdefault(qname, [])
                    if info.config.autoscaling_config:
                        self._autoscalers[qname] = AutoscalingState(
                            info.config.autoscaling_config
                        )
        except Exception:
            logger.warning(
                "controller checkpoint restore failed; starting with empty "
                "target state", exc_info=True,
            )

    @staticmethod
    def _version_of(spec: dict) -> str:
        """Code/arg identity only — scaling num_replicas or changing
        user_config must NOT roll replicas (user_config reconfigures in
        place, reference deployment_state semantics)."""
        import cloudpickle

        try:
            blob = cloudpickle.dumps(
                (spec["name"], spec["cls_or_fn"], spec.get("init_args"),
                 spec.get("init_kwargs"))
            )
        except Exception:
            blob = repr(spec).encode()
        return hashlib.sha1(blob).hexdigest()[:8]

"""Per-process long-poll subscriber for Serve membership updates.

Role-equivalent of python/ray/serve/_private/long_poll.py ::
LongPollClient. One background thread per process sits in
ServeController.poll_update (which blocks server-side until the membership
version advances); routers and proxies read the locally-cached snapshot —
zero RPCs on the request path, push-latency route/replica updates.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import ray_tpu
from ray_tpu.serve._private.common import CONTROLLER_NAME

_singleton: Optional["UpdateSubscriber"] = None
_singleton_lock = threading.Lock()


def get_subscriber() -> "UpdateSubscriber":
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            _singleton = UpdateSubscriber()
        return _singleton


def reset_subscriber() -> None:
    """Drop the cached subscriber (serve.shutdown / tests)."""
    global _singleton
    with _singleton_lock:
        sub, _singleton = _singleton, None
    if sub is not None:
        sub.stop()


class UpdateSubscriber:
    POLL_TIMEOUT_S = 10.0

    def __init__(self):
        self._lock = threading.Lock()
        self._snapshot: dict | None = None
        self._version = -1
        self._instance: str | None = None
        self._have_snapshot = threading.Event()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._loop, name="serve-longpoll", daemon=True
        )
        self._thread.start()

    # -- readers --------------------------------------------------------
    def wait_ready(self, timeout: float = 30.0) -> bool:
        return self._have_snapshot.wait(timeout)

    def get_routes(self) -> dict:
        self.wait_ready()
        with self._lock:
            return dict((self._snapshot or {}).get("routes", {}))

    def get_replicas(self, qualified_name: str) -> dict:
        self.wait_ready()
        with self._lock:
            replicas = (self._snapshot or {}).get("replicas", {})
            return dict(
                replicas.get(
                    qualified_name,
                    {"actor_names": [], "max_ongoing_requests": 100},
                )
            )

    def get_proxies(self) -> list:
        """Known ingress proxies: [{"name", "protocol", "host", "port"}].
        Clients use this to fail over between proxies (ISSUE 13)."""
        self.wait_ready()
        with self._lock:
            return list((self._snapshot or {}).get("proxies", []))

    def force_refresh(self) -> None:
        """Synchronous snapshot fetch for callers that cannot wait for the
        next push (e.g. a router spinning on scale-from-zero)."""
        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            update = ray_tpu.get(
                controller.poll_update.remote(-1, 0.0), timeout=30
            )
            self._apply(update)
        except Exception:  # rtlint: disable=swallowed-exception - opportunistic snapshot; the push path catches up
            pass

    def stop(self) -> None:
        self._stopped = True

    # -- internals ------------------------------------------------------
    def _apply(self, update: dict) -> None:
        with self._lock:
            instance = update.get("instance")
            if instance != self._instance:
                # Controller restarted: its version counter reset — resync.
                self._instance = instance
                self._version = -1
            if update["version"] >= self._version:
                self._version = update["version"]
                self._snapshot = {
                    "routes": update.get("routes", {}),
                    "replicas": update.get("replicas", {}),
                    "proxies": update.get("proxies", []),
                }
        self._have_snapshot.set()

    def _loop(self) -> None:
        backoff = 0.1
        while not self._stopped:
            try:
                controller = ray_tpu.get_actor(CONTROLLER_NAME)
                update = ray_tpu.get(
                    controller.poll_update.remote(
                        self._version, self.POLL_TIMEOUT_S
                    ),
                    timeout=self.POLL_TIMEOUT_S + 30,
                )
                self._apply(update)
                backoff = 0.1
            except Exception:
                # Controller missing/restarting: back off, keep serving the
                # stale snapshot (router falls back to force_refresh()).
                time.sleep(backoff)
                backoff = min(backoff * 2, 2.0)

"""HTTP proxy — the ingress actor.

Role-equivalent of python/ray/serve/_private/proxy.py :: ProxyActor +
proxy_router.py (SURVEY §2.6, §3.4): an aiohttp server per node mapping
route prefixes (refreshed from the controller) to deployment handles.
JSON bodies pass to the ingress deployment's __call__; responses are
JSON-encoded (bytes/str pass through). Health at /-/healthz, routes at
/-/routes.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import time
import threading
from typing import Any

import ray_tpu
from ray_tpu import exceptions
from ray_tpu._private import chaos
from ray_tpu._private.workload import LatencyHistogram
from ray_tpu.serve._private.common import (
    CONTROLLER_NAME,
    DEADLINE_HEADER,
    Deadline,
    reset_current_deadline,
    set_current_deadline,
)
from ray_tpu.serve._private.routing import RoutingMixin
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)


def parse_deadline_header(value: str | None, default_s: float) -> Deadline:
    """Ingress deadline: the X-RayTPU-Deadline header carries the client's
    remaining budget in seconds; absent or malformed, the route's default
    request timeout seeds it."""
    if value:
        try:
            return Deadline.after(float(value))
        except (TypeError, ValueError):
            pass
    return Deadline.after(default_s)


def admission_limit(num_replicas: int, max_ongoing: int,
                    max_queued: int) -> int:
    """Per-route in-flight ceiling at ONE ingress: steady-state capacity
    (replicas x max_ongoing) plus the configured queue allowance (-1
    derives a 1x-capacity queue). Past it, the proxy sheds with a fast
    503 + Retry-After instead of queueing to death."""
    capacity = max(1, num_replicas) * max(1, max_ongoing)
    allowance = capacity if max_queued < 0 else max_queued
    return capacity + allowance


class HTTPProxy(RoutingMixin):
    """Runs inside a ray_tpu actor; owns an aiohttp server on `port`."""

    ROUTE_REFRESH_S = 1.0
    # Flight-recorder snapshots (p50/p95/p99 per route) ride to the
    # controller workload store at most this often (ISSUE 8).
    STATS_FLUSH_S = 2.0

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._routes: dict[str, str] = {}
        self._handles: dict[str, Any] = {}
        self._last_refresh = 0.0
        self._num_requests = 0
        # Per-route in-flight counts for admission control (ISSUE 13).
        self._inflight: dict[str, int] = {}
        self._shed_count = 0
        # Per-route SLO accounting (ISSUE 8): bounded log-spaced
        # histograms + error counts, flushed as serve/<route> workload
        # series and recorded into the Prometheus pipeline per request.
        self._route_hist: dict[str, LatencyHistogram] = {}
        self._route_errors: dict[str, int] = {}
        self._route_flushed_count: dict[str, int] = {}
        self._last_stats_flush = time.monotonic()
        self._stats_lock = threading.Lock()
        self._started = threading.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread = threading.Thread(target=self._serve_forever, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("proxy HTTP server failed to start")

    # -- lifecycle ------------------------------------------------------
    def _serve_forever(self) -> None:
        asyncio.run(self._amain())

    async def _amain(self) -> None:
        from aiohttp import web

        self._loop = asyncio.get_running_loop()
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self._started.set()
        while True:
            await asyncio.sleep(3600)

    # -- request path ---------------------------------------------------

    async def _handle(self, request):
        from aiohttp import web

        path = "/" + request.match_info.get("tail", "")
        if path == "/-/healthz":
            return web.Response(text="ok")
        # Chaos hook (ISSUE 13): an armed "serve.proxy.kill" failpoint
        # takes this proxy down mid-request — the controller's health
        # check restarts it and clients fail over to a sibling proxy.
        try:
            chaos.failpoint("serve.proxy.kill")
        except chaos.ChaosFault:
            os._exit(1)
        if path == "/-/routes":
            await asyncio.to_thread(self._refresh_routes)
            return web.json_response(self._routes)
        await asyncio.to_thread(self._refresh_routes)
        match = self._match(path)
        if match is None:
            return web.Response(status=404, text=f"no route for {path}")
        _, qualified = match
        app_name, dep_name = qualified.split("_", 1)
        policy = self._route_policy(qualified)
        # Ingress deadline (ISSUE 13): the client's remaining budget rides
        # the X-RayTPU-Deadline header; everything downstream (handle
        # retries, replica, batching) derives its timeout from it.
        deadline = parse_deadline_header(
            request.headers.get(DEADLINE_HEADER),
            float(policy.get("request_timeout_s", 60.0)),
        )
        # Admission control: when the route's in-flight load projects past
        # capacity + queue allowance, shed fast with 503 + Retry-After.
        limit = admission_limit(
            policy.get("num_replicas", 1),
            policy.get("max_ongoing_requests", 100),
            policy.get("max_queued_requests", -1),
        )
        if self._inflight.get(qualified, 0) >= limit:
            return self._shed_response(qualified, "proxy", deadline=deadline)
        body: Any
        if request.method in ("POST", "PUT", "PATCH"):
            raw = await request.read()
            try:
                body = json.loads(raw) if raw else None
            except json.JSONDecodeError:
                body = raw
        else:
            body = dict(request.query)
        # Session affinity (ISSUE 17): an X-RayTPU-Session header (or a
        # "session_id" body field) becomes the handle's hash-ring key, so
        # a session's requests land on the replica holding its KV blocks
        # across every proxy in the pool (the ring is membership-keyed,
        # not proxy-local state).
        session_id = request.headers.get("X-RayTPU-Session", "")
        if not session_id and isinstance(body, dict):
            session_id = str(body.get("session_id", "") or "")
        self._num_requests += 1
        # Incoming trace context rides an X-RayTPU-Trace header
        # ("<trace_id>:<span_id>"); absent, the proxy starts a new trace.
        parent = None
        header = request.headers.get("X-RayTPU-Trace")
        if header and ":" in header:
            trace_id, _, span_id = header.partition(":")
            parent = {"trace_id": trace_id, "span_id": span_id}
        trace_scope = (
            tracing.span(
                f"serve.request {path}", parent=parent,
                method=request.method, route=qualified,
            )
            if tracing.enabled()
            else contextlib.nullcontext()
        )
        req_t0 = time.perf_counter()
        self._inflight[qualified] = self._inflight.get(qualified, 0) + 1
        try:
            try:
                # to_thread copies the contextvars context, so the
                # handle's dispatch sees this span as the current trace
                # parent and the deadline as the ambient budget.
                with trace_scope:
                    result = await asyncio.to_thread(
                        self._call_deployment, app_name, dep_name, body,
                        deadline, session_id,
                    )
            except exceptions.RequestShedError as exc:
                return self._shed_response(
                    qualified, "replica", deadline=deadline,
                    retry_after_s=getattr(exc, "retry_after_s", None),
                )
            except (exceptions.DeadlineExceededError, TimeoutError) as exc:
                self._observe_route(
                    qualified, time.perf_counter() - req_t0, error=True,
                    status="504",
                )
                return web.Response(
                    status=504, text=f"deadline exceeded: {exc}"
                )
            except RuntimeError as exc:
                if "no available replica" in str(exc):
                    # Backpressure/scale-to-zero exhausted the deadline:
                    # service unavailable, not an internal error.
                    return self._shed_response(
                        qualified, "proxy", deadline=deadline
                    )
                self._observe_route(
                    qualified, time.perf_counter() - req_t0, error=True
                )
                return web.Response(
                    status=500, text=f"{type(exc).__name__}: {exc}"
                )
            except Exception as exc:
                self._observe_route(
                    qualified, time.perf_counter() - req_t0, error=True
                )
                return web.Response(
                    status=500, text=f"{type(exc).__name__}: {exc}"
                )
            # For streams this is time-to-first-dispatch, not full-body
            # time: a token stream's lifetime measures the client's read
            # speed, not the serving SLO.
            self._observe_route(
                qualified, time.perf_counter() - req_t0, error=False
            )
            if time.monotonic() - self._last_stats_flush >= self.STATS_FLUSH_S:
                self._last_stats_flush = time.monotonic()
                asyncio.get_running_loop().create_task(
                    asyncio.to_thread(self._flush_route_stats)
                )
            from ray_tpu.serve.handle import ResponseStream

            if isinstance(result, ResponseStream):
                return await self._stream_response(request, result)
            if isinstance(result, bytes):
                return web.Response(body=result)
            if isinstance(result, str):
                return web.Response(text=result)
            try:
                return web.json_response(result)
            except TypeError:
                return web.Response(text=str(result))
        finally:
            count = self._inflight.get(qualified, 1)
            self._inflight[qualified] = max(0, count - 1)

    async def _stream_response(self, request, stream):
        """Streaming deployment → SSE (Accept: text/event-stream) or
        chunked newline-delimited body: the LLM token-stream ingress path
        (reference: proxy StreamingResponse support, SURVEY §3.4)."""
        from aiohttp import web

        sse = "text/event-stream" in request.headers.get("Accept", "")
        response = web.StreamResponse(
            headers={
                "Content-Type": (
                    "text/event-stream" if sse else "application/octet-stream"
                ),
                "Cache-Control": "no-cache",
            }
        )
        response.enable_chunked_encoding()
        await response.prepare(request)
        try:
            while True:
                # One thread hop per replica RPC, not per item.
                batch = await asyncio.to_thread(stream.next_batch)
                if not batch:
                    break
                for item in batch:
                    if isinstance(item, bytes):
                        text = item.decode("utf-8", "replace")
                    elif isinstance(item, str):
                        text = item
                    else:
                        try:
                            text = json.dumps(item)
                        except TypeError:
                            text = str(item)
                    if sse:
                        await response.write(f"data: {text}\n\n".encode())
                    else:
                        await response.write((text + "\n").encode())
        except BaseException:
            # Client disconnect, encode error, anything: release the
            # replica-side stream and the router's ongoing slot.
            await asyncio.to_thread(stream.cancel)
            raise
        await response.write_eof()
        return response

    def _call_deployment(self, app_name: str, dep_name: str, body: Any,
                         deadline: Deadline, session_id: str = "") -> Any:
        handle = self._handle_for(f"{app_name}_{dep_name}")
        if session_id:
            handle = handle.options(session_id=session_id)
        # Runs on a worker thread: the ambient deadline set here is what
        # handle.remote() picks up (and result() is bounded by it — no
        # more hardcoded 120s cap).
        token = set_current_deadline(deadline)
        try:
            return handle.remote(body).result()
        finally:
            reset_current_deadline(token)

    def _route_policy(self, qualified: str) -> dict:
        """Deployment policy + live replica count from the long-poll
        snapshot (push-updated; zero RPCs on the request path)."""
        from ray_tpu.serve._private.long_poll import get_subscriber

        info = get_subscriber().get_replicas(qualified)
        policy = dict(info.get("policy") or {})
        policy.setdefault(
            "max_ongoing_requests", info.get("max_ongoing_requests", 100)
        )
        policy["num_replicas"] = len(info.get("actor_names", ()))
        return policy

    def _shed_response(self, qualified: str, where: str,
                       deadline: Deadline | None = None,
                       retry_after_s: float | None = None):
        """Fast 503 + Retry-After: the graceful-degradation contract —
        callers back off instead of piling onto a saturated route. The
        Retry-After hint starts from the shedder's own estimate (the
        decode engine projects when a slot frees) and is capped by the
        request's remaining deadline budget — advising a client to retry
        after its own deadline would guarantee a wasted request."""
        from aiohttp import web

        self._shed_count += 1
        try:
            from ray_tpu.util import metrics as metrics_mod

            metrics_mod.inc_serve_reliability(
                "shed", route=qualified, where=where
            )
            metrics_mod.record_serve_request(qualified, 0.0, "503")
        except Exception:  # rtlint: disable=swallowed-exception - metric export must never fail a shed response
            pass
        hint = retry_after_s if retry_after_s is not None else 1.0
        if deadline is not None and not deadline.is_unbounded():
            hint = min(hint, deadline.remaining())
        hint = max(0.0, hint)
        return web.Response(
            status=503,
            headers={"Retry-After": f"{hint:.3f}"},
            text="overloaded: request shed by admission control",
        )

    # -- SLO accounting (ISSUE 8) ---------------------------------------
    def _observe_route(self, route: str, seconds: float, error: bool,
                       status: str | None = None) -> None:
        with self._stats_lock:
            hist = self._route_hist.get(route)
            if hist is None:
                hist = self._route_hist[route] = LatencyHistogram()
                self._route_last_flush_wall = getattr(
                    self, "_route_last_flush_wall", time.monotonic()
                )
            hist.observe(seconds)
            if error:
                self._route_errors[route] = (
                    self._route_errors.get(route, 0) + 1
                )
        try:
            from ray_tpu.util import metrics as metrics_mod

            metrics_mod.record_serve_request(
                route, seconds, status or ("500" if error else "200")
            )
        except Exception:
            # The request already succeeded; only the metric is lost.
            logger.debug("serve request metric record failed", exc_info=True)

    def get_route_stats(self) -> dict:
        """Per-route SLO snapshot: {route: {count, p50_ms, p95_ms,
        p99_ms, mean_ms, max_ms, errors}}."""
        with self._stats_lock:
            out = {}
            for route, hist in self._route_hist.items():
                snap = hist.snapshot()
                snap["errors"] = self._route_errors.get(route, 0)
                out[route] = snap
            return out

    def _flush_route_stats(self) -> None:
        """Push one serve/<route> workload sample per route to the
        controller flight-recorder store (best-effort: a flush lost to a
        controller blip only delays the next snapshot)."""
        now_wall = time.monotonic()
        last = getattr(self, "_route_last_flush_wall", now_wall)
        interval = max(now_wall - last, 1e-9)
        self._route_last_flush_wall = now_wall
        series = []
        ts = time.time()
        with self._stats_lock:
            for route, hist in self._route_hist.items():
                snap = hist.snapshot()
                prev = self._route_flushed_count.get(route, 0)
                self._route_flushed_count[route] = snap["count"]
                sample = {
                    "ts": ts,
                    "count": snap["count"],
                    "qps": (snap["count"] - prev) / interval,
                    "p50_ms": snap["p50_ms"],
                    "p95_ms": snap["p95_ms"],
                    "p99_ms": snap["p99_ms"],
                    "mean_ms": snap["mean_ms"],
                    "max_ms": snap["max_ms"],
                    "errors": self._route_errors.get(route, 0),
                }
                series.append(
                    {"key": f"serve/{route}", "samples": [sample]}
                )
        if not series:
            return
        try:
            from ray_tpu._private import worker as worker_mod

            ctx = worker_mod.get_global_context()
            ctx.io.run(
                ctx.controller.call(
                    "workload_ingest", {"series": series}, timeout=5.0
                )
            )
        except Exception:
            logger.debug(
                "route-stats flush to controller failed; next interval "
                "re-sends cumulative counts", exc_info=True,
            )

    # -- control --------------------------------------------------------
    def ready(self) -> str:
        return "ok"

    def get_num_requests(self) -> int:
        return self._num_requests

    def get_shed_count(self) -> int:
        return self._shed_count

"""HTTP proxy — the ingress actor.

Role-equivalent of python/ray/serve/_private/proxy.py :: ProxyActor +
proxy_router.py (SURVEY §2.6, §3.4): an aiohttp server per node mapping
route prefixes (refreshed from the controller) to deployment handles.
JSON bodies pass to the ingress deployment's __call__; responses are
JSON-encoded (bytes/str pass through). Health at /-/healthz, routes at
/-/routes.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import time
import threading
from typing import Any

import ray_tpu
from ray_tpu.serve._private.common import CONTROLLER_NAME
from ray_tpu.serve._private.routing import RoutingMixin
from ray_tpu.util import tracing


class HTTPProxy(RoutingMixin):
    """Runs inside a ray_tpu actor; owns an aiohttp server on `port`."""

    ROUTE_REFRESH_S = 1.0

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._routes: dict[str, str] = {}
        self._handles: dict[str, Any] = {}
        self._last_refresh = 0.0
        self._num_requests = 0
        self._started = threading.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread = threading.Thread(target=self._serve_forever, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("proxy HTTP server failed to start")

    # -- lifecycle ------------------------------------------------------
    def _serve_forever(self) -> None:
        asyncio.run(self._amain())

    async def _amain(self) -> None:
        from aiohttp import web

        self._loop = asyncio.get_running_loop()
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self._started.set()
        while True:
            await asyncio.sleep(3600)

    # -- request path ---------------------------------------------------

    async def _handle(self, request):
        from aiohttp import web

        path = "/" + request.match_info.get("tail", "")
        if path == "/-/healthz":
            return web.Response(text="ok")
        if path == "/-/routes":
            await asyncio.to_thread(self._refresh_routes)
            return web.json_response(self._routes)
        await asyncio.to_thread(self._refresh_routes)
        match = self._match(path)
        if match is None:
            return web.Response(status=404, text=f"no route for {path}")
        _, qualified = match
        app_name, dep_name = qualified.split("_", 1)
        body: Any
        if request.method in ("POST", "PUT", "PATCH"):
            raw = await request.read()
            try:
                body = json.loads(raw) if raw else None
            except json.JSONDecodeError:
                body = raw
        else:
            body = dict(request.query)
        self._num_requests += 1
        # Incoming trace context rides an X-RayTPU-Trace header
        # ("<trace_id>:<span_id>"); absent, the proxy starts a new trace.
        parent = None
        header = request.headers.get("X-RayTPU-Trace")
        if header and ":" in header:
            trace_id, _, span_id = header.partition(":")
            parent = {"trace_id": trace_id, "span_id": span_id}
        trace_scope = (
            tracing.span(
                f"serve.request {path}", parent=parent,
                method=request.method, route=qualified,
            )
            if tracing.enabled()
            else contextlib.nullcontext()
        )
        try:
            # to_thread copies the contextvars context, so the handle's
            # dispatch sees this span as the current trace parent.
            with trace_scope:
                result = await asyncio.to_thread(
                    self._call_deployment, app_name, dep_name, body
                )
        except Exception as exc:
            return web.Response(status=500, text=f"{type(exc).__name__}: {exc}")
        from ray_tpu.serve.handle import ResponseStream

        if isinstance(result, ResponseStream):
            return await self._stream_response(request, result)
        if isinstance(result, bytes):
            return web.Response(body=result)
        if isinstance(result, str):
            return web.Response(text=result)
        try:
            return web.json_response(result)
        except TypeError:
            return web.Response(text=str(result))

    async def _stream_response(self, request, stream):
        """Streaming deployment → SSE (Accept: text/event-stream) or
        chunked newline-delimited body: the LLM token-stream ingress path
        (reference: proxy StreamingResponse support, SURVEY §3.4)."""
        from aiohttp import web

        sse = "text/event-stream" in request.headers.get("Accept", "")
        response = web.StreamResponse(
            headers={
                "Content-Type": (
                    "text/event-stream" if sse else "application/octet-stream"
                ),
                "Cache-Control": "no-cache",
            }
        )
        response.enable_chunked_encoding()
        await response.prepare(request)
        try:
            while True:
                # One thread hop per replica RPC, not per item.
                batch = await asyncio.to_thread(stream.next_batch)
                if not batch:
                    break
                for item in batch:
                    if isinstance(item, bytes):
                        text = item.decode("utf-8", "replace")
                    elif isinstance(item, str):
                        text = item
                    else:
                        try:
                            text = json.dumps(item)
                        except TypeError:
                            text = str(item)
                    if sse:
                        await response.write(f"data: {text}\n\n".encode())
                    else:
                        await response.write((text + "\n").encode())
        except BaseException:
            # Client disconnect, encode error, anything: release the
            # replica-side stream and the router's ongoing slot.
            await asyncio.to_thread(stream.cancel)
            raise
        await response.write_eof()
        return response

    def _call_deployment(self, app_name: str, dep_name: str, body: Any) -> Any:
        handle = self._handle_for(f"{app_name}_{dep_name}")
        return handle.remote(body).result(timeout=120)

    # -- control --------------------------------------------------------
    def ready(self) -> str:
        return "ok"

    def get_num_requests(self) -> int:
        return self._num_requests

"""gRPC ingress — the second proxy protocol.

Role-equivalent of the reference proxy's gRPC server
(python/ray/serve/_private/proxy.py gRPC path, SURVEY §2.6): a grpc.aio
server per node exposing Serve applications over two generic methods —
no compiled user protos required (the reference routes user-defined
protos; here the generic-bytes envelope keeps the ingress
schema-agnostic, with JSON as the payload convention):

  /raytpu.serve.Serve/Predict        (unary)    route+payload → result
  /raytpu.serve.Serve/PredictStream  (server streaming) one message per
                                     item of a streaming deployment
                                     (LLM token streaming over gRPC)

Request bytes: JSON {"route": "/app", "data": <payload>}; response
bytes: JSON result (bytes results pass through raw). Routing, handles,
and long-poll route refresh are shared with the HTTP proxy.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Any

SERVICE = "raytpu.serve.Serve"


from ray_tpu import exceptions  # noqa: E402
from ray_tpu.serve._private.common import (  # noqa: E402
    DEADLINE_METADATA_KEY,
    Deadline,
    reset_current_deadline,
    set_current_deadline,
)
from ray_tpu.serve._private.routing import RoutingMixin  # noqa: E402


class GRPCProxy(RoutingMixin):
    """Runs inside the proxy actor beside the HTTP server."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9000):
        self.host = host
        self.port = port
        self._routes: dict[str, str] = {}
        self._handles: dict[str, Any] = {}
        self._num_requests = 0
        self._started = threading.Event()
        self._start_error: Exception | None = None
        self._thread = threading.Thread(target=self._serve_forever, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError(
                f"gRPC proxy failed to start: {self._start_error}"
            )
        if self._start_error is not None:
            raise self._start_error

    def _serve_forever(self) -> None:
        try:
            asyncio.run(self._amain())
        except Exception as exc:
            self._start_error = exc
            self._started.set()

    async def _amain(self) -> None:
        import grpc

        server = grpc.aio.server()

        def unary(method):
            return grpc.unary_unary_rpc_method_handler(
                method,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            )

        def streaming(method):
            return grpc.unary_stream_rpc_method_handler(
                method,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            )

        handler = grpc.method_handlers_generic_handler(
            SERVICE,
            {
                "Predict": unary(self._predict),
                "PredictStream": streaming(self._predict_stream),
                "Healthz": unary(self._healthz),
            },
        )
        server.add_generic_rpc_handlers((handler,))
        bound = server.add_insecure_port(f"{self.host}:{self.port}")
        if bound == 0:
            raise RuntimeError(f"gRPC proxy could not bind {self.port}")
        self.port = bound
        await server.start()
        self._started.set()
        await server.wait_for_termination()

    # Routing/_match/_handle_for come from RoutingMixin.

    def _resolve(self, raw_request: bytes) -> tuple[Any, Any, str]:
        """→ (handle, data, qualified). Raises ValueError for bad requests."""
        try:
            request = json.loads(raw_request or b"{}")
        except json.JSONDecodeError as exc:
            raise ValueError(f"request must be JSON: {exc}")
        if not isinstance(request, dict):
            raise ValueError(
                f"request must be a JSON object, got {type(request).__name__}"
            )
        route = request.get("route", "/")
        self._refresh_routes()
        match = self._match(route)
        if match is None:
            raise LookupError(f"no Serve route for {route!r}")
        _, qualified = match
        return self._handle_for(qualified), request.get("data"), qualified

    def _ingress_deadline(self, context, qualified: str) -> Deadline:
        """gRPC carries TWO deadline signals: the protocol-level client
        deadline (context.time_remaining()) and the explicit
        x-raytpu-deadline metadata budget. The tighter one wins; absent
        both, the deployment's request_timeout_s seeds it."""
        budgets = []
        try:
            remaining = context.time_remaining()
            if remaining is not None:
                budgets.append(float(remaining))
        except Exception:  # rtlint: disable=swallowed-exception - context without a client deadline: fall through to metadata/config
            pass
        try:
            for key, value in context.invocation_metadata() or ():
                if key.lower() == DEADLINE_METADATA_KEY:
                    budgets.append(float(value))
        except (TypeError, ValueError):  # rtlint: disable=swallowed-exception - malformed metadata budget: fall through to config default
            pass
        if not budgets:
            from ray_tpu.serve._private.long_poll import get_subscriber

            policy = get_subscriber().get_replicas(qualified).get(
                "policy"
            ) or {}
            budgets.append(float(policy.get("request_timeout_s", 60.0)))
        return Deadline.after(min(budgets))

    @staticmethod
    def _call_with_deadline(handle, data, deadline: Deadline):
        """Worker-thread body: anchor the ambient deadline, dispatch, and
        let result() derive every timeout from it."""
        token = set_current_deadline(deadline)
        try:
            return handle.remote(data).result()
        finally:
            reset_current_deadline(token)

    @staticmethod
    def _encode(item: Any) -> bytes:
        if isinstance(item, bytes):
            return item
        try:
            return json.dumps(item).encode()
        except TypeError:
            return str(item).encode()

    # -- RPC methods -----------------------------------------------------
    async def _healthz(self, request: bytes, context) -> bytes:
        return b"ok"

    async def _predict(self, request: bytes, context) -> bytes:
        import grpc

        self._num_requests += 1
        try:
            handle, data, qualified = await asyncio.to_thread(
                self._resolve, request
            )
            deadline = self._ingress_deadline(context, qualified)
            result = await asyncio.to_thread(
                self._call_with_deadline, handle, data, deadline
            )
        except LookupError as exc:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(exc))
        except ValueError as exc:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        except exceptions.RequestShedError as exc:
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(exc))
        except (exceptions.DeadlineExceededError, TimeoutError) as exc:
            await context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(exc))
        except Exception as exc:
            await context.abort(
                grpc.StatusCode.INTERNAL, f"{type(exc).__name__}: {exc}"
            )
        from ray_tpu.serve.handle import ResponseStream

        if isinstance(result, ResponseStream):
            # Unary caller asked a streaming deployment: drain into one blob.
            chunks: list = []
            try:
                while True:
                    batch = await asyncio.to_thread(result.next_batch)
                    if not batch:
                        break
                    chunks.extend(batch)
            except BaseException as exc:
                await asyncio.to_thread(result.cancel)
                await context.abort(
                    grpc.StatusCode.INTERNAL,
                    f"stream failed: {type(exc).__name__}: {exc}",
                )
            return self._encode(chunks)
        return self._encode(result)

    async def _predict_stream(self, request: bytes, context):
        import grpc

        self._num_requests += 1
        try:
            handle, data, qualified = await asyncio.to_thread(
                self._resolve, request
            )
            deadline = self._ingress_deadline(context, qualified)
            result = await asyncio.to_thread(
                self._call_with_deadline, handle, data, deadline
            )
        except LookupError as exc:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(exc))
            return
        except ValueError as exc:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
            return
        except exceptions.RequestShedError as exc:
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(exc))
            return
        except (exceptions.DeadlineExceededError, TimeoutError) as exc:
            await context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(exc))
            return
        except Exception as exc:
            await context.abort(
                grpc.StatusCode.INTERNAL, f"{type(exc).__name__}: {exc}"
            )
            return
        from ray_tpu.serve.handle import ResponseStream

        if not isinstance(result, ResponseStream):
            yield self._encode(result)
            return
        try:
            while True:
                batch = await asyncio.to_thread(result.next_batch)
                if not batch:
                    break
                for item in batch:
                    yield self._encode(item)
        except BaseException as exc:
            await asyncio.to_thread(result.cancel)
            await context.abort(
                grpc.StatusCode.INTERNAL,
                f"stream failed: {type(exc).__name__}: {exc}",
            )

    def get_num_requests(self) -> int:
        return self._num_requests

"""Autoscaling policy — pure math, table-testable.

Role-equivalent of python/ray/serve/_private/autoscaling_policy.py ::
_calculate_desired_num_replicas and autoscaling_state.py's delay logic
(SURVEY §2.6): desired = ceil(total_ongoing / target), smoothed, clamped to
[min, max]; upscale/downscale only after the respective delay has been
continuously satisfied.
"""

from __future__ import annotations

import math
import time

from ray_tpu.serve._private.common import AutoscalingConfig


def calculate_desired_num_replicas(
    config: AutoscalingConfig,
    total_ongoing_requests: float,
    current_replicas: int,
    queue_depth: float = 0.0,
    p99_ms: float | None = None,
    kv_free_frac: float | None = None,
) -> int:
    # Demand counts queued-but-unstarted work too (ISSUE 13): a deployment
    # whose batching queues are backing up is under-provisioned even while
    # `ongoing` sits at target. queue_weight tunes how aggressively queue
    # depth converts to replicas.
    demand = total_ongoing_requests + getattr(
        config, "queue_weight", 1.0
    ) * max(0.0, queue_depth)
    if current_replicas == 0:
        # Scale from zero on any traffic.
        raw = 1 if demand > 0 else 0
    else:
        per_replica = demand / current_replicas
        error_ratio = per_replica / config.target_ongoing_requests
        factor = (
            config.upscale_smoothing_factor
            if error_ratio > 1
            else config.downscale_smoothing_factor
        )
        smoothed = 1 + factor * (error_ratio - 1)
        raw = math.ceil(current_replicas * smoothed - 1e-9)
    # SLO input (ISSUE 8 histograms → ISSUE 13 autoscaler): a breached
    # p99 target forces at least one more replica even when the ongoing
    # count looks healthy — tail latency is load the gauge can't see.
    slo = getattr(config, "slo_p99_ms", None)
    if slo and p99_ms is not None and p99_ms > slo and current_replicas > 0:
        raw = max(raw, current_replicas + 1)
    # Memory floor (ISSUE 17): a decode pool whose worst replica is out
    # of KV-block headroom stalls admission regardless of ongoing
    # counts — the serve-plane twin of the PR-5 HBM headroom guard.
    headroom = getattr(config, "kv_headroom_min", None)
    if (
        headroom is not None
        and kv_free_frac is not None
        and kv_free_frac < headroom
        and current_replicas > 0
    ):
        raw = max(raw, current_replicas + 1)
    return max(config.min_replicas, min(config.max_replicas, raw))


class AutoscalingState:
    """Tracks the decision over time, enforcing up/downscale delays."""

    def __init__(self, config: AutoscalingConfig):
        self.config = config
        self._proposal: int | None = None
        self._proposal_since: float = 0.0

    def decide(
        self,
        total_ongoing_requests: float,
        current_replicas: int,
        now: float | None = None,
        queue_depth: float = 0.0,
        p99_ms: float | None = None,
        kv_free_frac: float | None = None,
    ) -> int:
        now = time.monotonic() if now is None else now
        desired = calculate_desired_num_replicas(
            self.config, total_ongoing_requests, current_replicas,
            queue_depth=queue_depth, p99_ms=p99_ms,
            kv_free_frac=kv_free_frac,
        )
        if desired == current_replicas:
            self._proposal = None
            return current_replicas
        if desired != self._proposal:
            self._proposal = desired
            self._proposal_since = now
            return current_replicas
        delay = (
            self.config.upscale_delay_s
            if desired > current_replicas
            else self.config.downscale_delay_s
        )
        if now - self._proposal_since >= delay:
            self._proposal = None
            return desired
        return current_replicas

"""Shared Serve vocabulary (deployment configs, statuses, request metadata).

Role-equivalent of python/ray/serve/_private/common.py + config dataclasses
from python/ray/serve/config.py.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
DEFAULT_APP_NAME = "default"


@dataclass
class AutoscalingConfig:
    """reference: ray.serve.config.AutoscalingConfig — desired replicas =
    total ongoing requests / target_ongoing_requests, smoothed + clamped."""

    min_replicas: int = 1
    max_replicas: int = 10
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0
    upscale_smoothing_factor: float = 1.0
    downscale_smoothing_factor: float = 1.0
    metrics_interval_s: float = 1.0


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 100
    user_config: Any = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 10.0
    health_check_timeout_s: float = 30.0
    graceful_shutdown_timeout_s: float = 20.0
    ray_actor_options: dict = field(default_factory=dict)
    max_batch_queue: int = 1000


@dataclass
class DeploymentInfo:
    name: str
    app_name: str
    config: DeploymentConfig
    cls_or_fn: Any = None
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)
    version: str = ""
    route_prefix: Optional[str] = None

    def qualified_name(self) -> str:
        return f"{self.app_name}_{self.name}"


@dataclass
class ReplicaInfo:
    replica_id: str
    deployment: str  # qualified name
    actor_name: str
    state: str = "STARTING"  # STARTING/RUNNING/DRAINING/STOPPING/DEAD
    version: str = ""
    started_at: float = field(default_factory=time.time)


@dataclass
class RequestMetadata:
    request_id: str = field(default_factory=lambda: uuid.uuid4().hex[:12])
    method_name: str = "__call__"
    multiplexed_model_id: str = ""
    http: bool = False


def new_replica_id(deployment: str) -> str:
    return f"{deployment}#{uuid.uuid4().hex[:6]}"

"""Shared Serve vocabulary (deployment configs, statuses, request metadata).

Role-equivalent of python/ray/serve/_private/common.py + config dataclasses
from python/ray/serve/config.py.
"""

from __future__ import annotations

import contextvars
import math
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
DEFAULT_APP_NAME = "default"

# HTTP header / gRPC metadata key carrying the request's remaining budget in
# seconds (a relative duration, NOT a wall-clock timestamp: monotonic clocks
# don't agree across processes, so each hop re-anchors locally).
DEADLINE_HEADER = "X-RayTPU-Deadline"
DEADLINE_METADATA_KEY = "x-raytpu-deadline"


@dataclass(frozen=True)
class Deadline:
    """A point on this process's monotonic clock by which the request must
    finish. Created once at ingress and threaded through proxy -> handle ->
    replica -> batching; every serve-path timeout derives from it.

    ``at_monotonic`` is ``math.inf`` for unbounded requests, so arithmetic
    (remaining/expired) works without None-checks.
    """

    at_monotonic: float = math.inf

    @classmethod
    def after(cls, budget_s: Optional[float]) -> "Deadline":
        if budget_s is None:
            return cls(math.inf)
        return cls(time.monotonic() + max(0.0, float(budget_s)))

    @classmethod
    def never(cls) -> "Deadline":
        return cls(math.inf)

    def is_unbounded(self) -> bool:
        return math.isinf(self.at_monotonic)

    def remaining(self, cap: Optional[float] = None) -> float:
        """Seconds left (>= 0). ``cap`` tightens the result — the idiom for
        deriving a sub-operation timeout from the request deadline."""
        left = self.at_monotonic - time.monotonic()
        if cap is not None:
            left = min(left, cap)
        return max(0.0, left)

    def expired(self) -> bool:
        return self.at_monotonic - time.monotonic() <= 0.0

    def budget(self) -> Optional[float]:
        """Remaining budget for the wire (header/metadata/meta dict); None
        when unbounded. The receiving hop re-anchors with ``after()``."""
        if self.is_unbounded():
            return None
        return self.remaining()


_current_deadline: contextvars.ContextVar[Optional[Deadline]] = (
    contextvars.ContextVar("raytpu_serve_deadline", default=None)
)


def current_deadline() -> Optional[Deadline]:
    return _current_deadline.get()


def set_current_deadline(deadline: Optional[Deadline]):
    """Sets the ambient request deadline; returns a contextvar token the
    caller must hand to ``reset_current_deadline``."""
    return _current_deadline.set(deadline)


def reset_current_deadline(token) -> None:
    _current_deadline.reset(token)


@dataclass
class RetryPolicy:
    """Per-deployment retry budget (replaces the old retry-once handoff).

    Attempts are spent only while the request deadline has budget left;
    backoff between attempts is full-jitter via util/backoff.Backoff, capped
    by the remaining deadline. ``hedge`` arms tail-latency hedging: a second
    attempt launches once the first has been in flight for ``hedge_after_s``
    (or the route's observed p95 when None) and the loser is cancelled.
    """

    max_attempts: int = 3
    initial_backoff_s: float = 0.02
    max_backoff_s: float = 1.0
    retry_on_timeout: bool = False
    hedge: bool = False
    hedge_after_s: Optional[float] = None

    @classmethod
    def from_dict(cls, d: dict) -> "RetryPolicy":
        known = {k: v for k, v in d.items() if k in cls.__dataclass_fields__}
        return cls(**known)


@dataclass
class AutoscalingConfig:
    """reference: ray.serve.config.AutoscalingConfig — desired replicas =
    total ongoing requests / target_ongoing_requests, smoothed + clamped."""

    min_replicas: int = 1
    max_replicas: int = 10
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0
    upscale_smoothing_factor: float = 1.0
    downscale_smoothing_factor: float = 1.0
    metrics_interval_s: float = 1.0
    # Closed-loop inputs (ISSUE 13): queued-but-unstarted requests count
    # toward demand with this weight, and a route p99 above ``slo_p99_ms``
    # forces at least one replica of upscale pressure even when ongoing
    # counts look healthy (queues hide behind batching).
    queue_weight: float = 1.0
    slo_p99_ms: Optional[float] = None
    # Memory floor (ISSUE 17 tentpole d): when the fleet's minimum
    # KV-block free fraction drops below this, force one replica of
    # upscale pressure — the decode-pool analogue of the PR-5 HBM
    # headroom signal (a full KV pool stalls admission long before
    # ongoing counts look unhealthy).
    kv_headroom_min: Optional[float] = None


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 100
    user_config: Any = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 10.0
    health_check_timeout_s: float = 30.0
    graceful_shutdown_timeout_s: float = 20.0
    ray_actor_options: dict = field(default_factory=dict)
    max_batch_queue: int = 1000
    # Reliability knobs (ISSUE 13). ``request_timeout_s`` seeds the Deadline
    # when the caller didn't propagate one; ``health_probe_timeout_s`` bounds
    # the liveness probe the handle runs before surfacing a bare timeout
    # (was a hardcoded 5s); ``max_queued_requests`` is the per-route
    # admission allowance above steady-state capacity (-1 derives 1x
    # capacity, 0 disables queueing entirely).
    request_timeout_s: float = 60.0
    health_probe_timeout_s: float = 5.0
    max_queued_requests: int = -1
    retry_policy: RetryPolicy = field(default_factory=RetryPolicy)

    def policy_snapshot(self) -> dict:
        """The config subset routers/proxies need, published with the
        membership snapshot so every hop prices timeouts off deployment
        config instead of hardcoded constants."""
        from dataclasses import asdict

        return {
            "max_ongoing_requests": self.max_ongoing_requests,
            "request_timeout_s": self.request_timeout_s,
            "health_probe_timeout_s": self.health_probe_timeout_s,
            "max_queued_requests": self.max_queued_requests,
            "graceful_shutdown_timeout_s": self.graceful_shutdown_timeout_s,
            "retry_policy": asdict(self.retry_policy),
        }


@dataclass
class DeploymentInfo:
    name: str
    app_name: str
    config: DeploymentConfig
    cls_or_fn: Any = None
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)
    version: str = ""
    route_prefix: Optional[str] = None

    def qualified_name(self) -> str:
        return f"{self.app_name}_{self.name}"


@dataclass
class ReplicaInfo:
    replica_id: str
    deployment: str  # qualified name
    actor_name: str
    state: str = "STARTING"  # STARTING/RUNNING/DRAINING/STOPPING/DEAD
    version: str = ""
    started_at: float = field(default_factory=time.time)
    # Which node hosts the replica actor — lets the controller map
    # oom_risk telemetry events (keyed by node_id) to draining candidates.
    node_id: str = ""


@dataclass
class RequestMetadata:
    request_id: str = field(default_factory=lambda: uuid.uuid4().hex[:12])
    method_name: str = "__call__"
    multiplexed_model_id: str = ""
    # Hash-ring affinity key (ISSUE 17): a session's requests rendezvous-
    # hash to the replica holding its KV blocks / conversation state.
    session_id: str = ""
    http: bool = False
    # Remaining deadline budget at dispatch time (seconds, None=unbounded).
    # Relative on the wire; the replica re-anchors on its own clock.
    deadline_budget_s: Optional[float] = None
    # Attempt ordinal (0 = first try) so replicas/tracing can tell retries
    # and hedges apart from fresh requests.
    attempt: int = 0


def new_replica_id(deployment: str) -> str:
    return f"{deployment}#{uuid.uuid4().hex[:6]}"

"""Shared ingress routing: long-poll-refreshed route table + handle cache,
plus the replica-selection hash ring (ISSUE 17).

One implementation of route matching and deployment-handle resolution for
every proxy protocol (HTTP, gRPC) — reference proxy_router.py role. A
future change to prefix-matching or the qualified-name encoding lands in
both ingresses at once.

``HashRing`` replaces power-of-two-choices replica selection: rendezvous
(highest-random-weight) hashing keyed on the request's affinity key
(session id > multiplexed model id > shape key > request id) with a
bounded-load fallback. Keyed traffic (a session's decode stream, a
multiplexed model's requests) sticks to one replica — so its KV blocks
and LRU-loaded model stay hot — while replica add/remove only remaps the
keys that must move (HRW's minimal-disruption property). Bounded load
walks down the key's preference order past saturated replicas, so a hot
session cannot melt one replica while others idle.
"""

from __future__ import annotations

import hashlib
from typing import Any, Iterable, Mapping, Optional


class HashRing:
    """Rendezvous-hash replica selector with bounded-load fallback.

    Pure data structure (no locks, no RPC): callers pass the current
    member list and per-member load on every pick, so the ring never
    holds stale membership — Router._refresh already owns that state.
    """

    def __init__(self, members: Iterable[str] = ()):
        self._members: tuple[str, ...] = tuple(sorted(members))

    def update(self, members: Iterable[str]) -> None:
        self._members = tuple(sorted(members))

    @property
    def members(self) -> tuple[str, ...]:
        return self._members

    @staticmethod
    def _score(key: str, member: str) -> int:
        # blake2b over "key|member": stable across processes and runs
        # (unlike hash()), cheap, and uniformly distributed.
        digest = hashlib.blake2b(
            f"{key}|{member}".encode(), digest_size=8
        ).digest()
        return int.from_bytes(digest, "big")

    def rank(self, key: str) -> list[str]:
        """Members ordered by descending HRW score for ``key`` — the
        key's full preference order. Removing a member leaves every
        other member's relative order untouched, which is exactly the
        ring-stability property the tests pin down."""
        return sorted(
            self._members, key=lambda m: self._score(key, m), reverse=True
        )

    def pick(
        self,
        key: str,
        load: Optional[Mapping[str, int]] = None,
        max_load: Optional[int] = None,
    ) -> Optional[str]:
        """The key's most-preferred member whose load is under
        ``max_load``. Saturated members are skipped in preference order
        (bounded-load fallback); if every member is saturated, fall back
        to the least-loaded one so the caller can apply its own
        backpressure rather than spin."""
        order = self.rank(key)
        if not order:
            return None
        if load is None or max_load is None:
            return order[0]
        for member in order:
            if load.get(member, 0) < max_load:
                return member
        return min(order, key=lambda m: load.get(m, 0))


class RoutingMixin:
    """State: ``self._routes`` dict + ``self._handles`` cache."""

    _routes: dict
    _handles: dict

    def _refresh_routes(self) -> None:
        # Routes arrive by long-poll push (no per-request controller RPC).
        from ray_tpu.serve._private.long_poll import get_subscriber

        self._routes = get_subscriber().get_routes()

    def _match(self, path: str) -> Optional[tuple[str, str]]:
        """Longest-prefix route match → (route, qualified deployment)."""
        best = None
        for route, deployment in self._routes.items():
            if (
                path == route
                or path.startswith(route.rstrip("/") + "/")
                or route == "/"
            ):
                if best is None or len(route) > len(best[0]):
                    best = (route, deployment)
        return best

    def _handle_for(self, qualified: str) -> Any:
        """Cached DeploymentHandle for an ``<app>_<deployment>`` name."""
        handle = self._handles.get(qualified)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            app_name, dep_name = qualified.split("_", 1)
            handle = DeploymentHandle(dep_name, app_name)
            self._handles[qualified] = handle
        return handle

"""Shared ingress routing: long-poll-refreshed route table + handle cache.

One implementation of route matching and deployment-handle resolution for
every proxy protocol (HTTP, gRPC) — reference proxy_router.py role. A
future change to prefix-matching or the qualified-name encoding lands in
both ingresses at once.
"""

from __future__ import annotations

from typing import Any, Optional


class RoutingMixin:
    """State: ``self._routes`` dict + ``self._handles`` cache."""

    _routes: dict
    _handles: dict

    def _refresh_routes(self) -> None:
        # Routes arrive by long-poll push (no per-request controller RPC).
        from ray_tpu.serve._private.long_poll import get_subscriber

        self._routes = get_subscriber().get_routes()

    def _match(self, path: str) -> Optional[tuple[str, str]]:
        """Longest-prefix route match → (route, qualified deployment)."""
        best = None
        for route, deployment in self._routes.items():
            if (
                path == route
                or path.startswith(route.rstrip("/") + "/")
                or route == "/"
            ):
                if best is None or len(route) > len(best[0]):
                    best = (route, deployment)
        return best

    def _handle_for(self, qualified: str) -> Any:
        """Cached DeploymentHandle for an ``<app>_<deployment>`` name."""
        handle = self._handles.get(qualified)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            app_name, dep_name = qualified.split("_", 1)
            handle = DeploymentHandle(dep_name, app_name)
            self._handles[qualified] = handle
        return handle

"""Replica — the actor hosting one copy of a deployment.

Role-equivalent of python/ray/serve/_private/replica.py ::
UserCallableWrapper (SURVEY §2.6): constructs the user class (resolving
DeploymentHandle placeholders for model composition), serves requests with
an ongoing-request gauge (max_ongoing_requests backpressure lives in the
router), supports reconfigure(user_config), health checks, and multiplexed
model loading.
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import logging
import os
import signal
import time
from typing import Any

from ray_tpu import exceptions
from ray_tpu._private import chaos
from ray_tpu._private.workload import LatencyHistogram
from ray_tpu.serve._private.common import (
    Deadline,
    reset_current_deadline,
    set_current_deadline,
)
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

_request_context: contextvars.ContextVar = contextvars.ContextVar(
    "serve_request_context", default=None
)


def get_current_request_metadata():
    return _request_context.get()


class Replica:
    """Runs inside a ray_tpu actor with max_concurrency > 1."""

    def __init__(
        self,
        replica_id: str,
        deployment_name: str,
        cls_or_fn: Any,
        init_args: tuple,
        init_kwargs: dict,
        user_config: Any,
        version: str,
        limits: dict | None = None,
    ):
        from ray_tpu.serve.handle import _resolve_handle_placeholders

        self.replica_id = replica_id
        self.deployment_name = deployment_name
        self.version = version
        self._ongoing = 0
        self._total = 0
        self._shed = 0
        # Deployment-config subset the replica enforces locally (admission
        # + drain timing); the controller passes it at construction.
        limits = limits or {}
        self._max_ongoing = int(limits.get("max_ongoing_requests", 100))
        max_queued = int(limits.get("max_queued_requests", -1))
        # Admission ceiling: steady-state capacity plus the queue
        # allowance (-1 derives a 1x-capacity queue). The router already
        # enforces max_ongoing per client — this guard catches the
        # multi-proxy overcommit case where N routers each grant
        # max_ongoing slots in good faith.
        self._admission_limit = self._max_ongoing + (
            self._max_ongoing if max_queued < 0 else max_queued
        )
        self._graceful_shutdown_timeout_s = float(
            limits.get("graceful_shutdown_timeout_s", 20.0)
        )
        self._draining = False
        # SIGTERM means "the platform wants this process gone soon": stop
        # accepting work and let in-flight requests finish instead of
        # dying mid-request. Actor tasks may run off the main thread, so
        # installation is best-effort.
        try:
            signal.signal(signal.SIGTERM, self._on_sigterm)
        except (ValueError, OSError):  # rtlint: disable=swallowed-exception - non-main thread / unsupported platform: drain still reachable via the drain() RPC
            pass
        # Bounded log-spaced histogram (ISSUE 8) instead of a raw latency
        # list: O(1) memory for any request volume, p50/p95/p99 over the
        # replica's WHOLE life rather than the last 200 samples.
        self._latency_hist = LatencyHistogram()
        self._streams: dict[str, tuple] = {}
        self._stream_counter = 0
        # Per-incarnation stream-id fencing: replica_id is stable across
        # restarts and the counter resets with the process, so without
        # this token a caller holding "stream-<replica>-0" from a dead
        # incarnation could alias a NEW stream of the restarted replica
        # and silently read someone else's tokens. With it, stale ids
        # miss the table and get the loud "unknown stream" terminal.
        import uuid as _uuid

        self._incarnation = _uuid.uuid4().hex[:6]
        # Shape keys served here (explicit request shape_keys); unioned
        # with the batching module's compiled buckets in
        # get_warm_shapes() for compile-cache-aware routing.
        self._warm_shapes: set[str] = set()
        init_args = _resolve_handle_placeholders(init_args)
        init_kwargs = _resolve_handle_placeholders(init_kwargs)
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = cls_or_fn
            self._is_function = True
        # serve_llm integration (ISSUE 17): a callable hosting a decode
        # engine gets its gauge identity stamped here (the engine can't
        # know which replica hosts it at construction time).
        engine = getattr(self._callable, "_engine", None)
        if engine is not None and hasattr(engine, "replica_id"):
            engine.deployment = deployment_name
            engine.replica_id = replica_id
        if user_config is not None:
            self._apply_reconfigure(user_config)

    # -- request path ---------------------------------------------------
    async def handle_request(self, meta: dict, args: tuple, kwargs: dict) -> Any:
        if not (tracing.enabled() and meta.get("trace_ctx")):
            return await self._handle_request_inner(meta, args, kwargs)
        with tracing.span(
            f"serve.replica {self.deployment_name}",
            parent=meta["trace_ctx"],
            replica_id=self.replica_id,
            request_id=meta.get("request_id"),
        ):
            return await self._handle_request_inner(meta, args, kwargs)

    async def _handle_request_inner(
        self, meta: dict, args: tuple, kwargs: dict
    ) -> Any:
        for arg in args:
            if isinstance(arg, dict) and "__serve_stream__" in arg:
                raise TypeError(
                    "a streaming deployment response cannot be composed "
                    "into a downstream call — iterate the stream in the "
                    "caller and pass materialized values"
                )
        # Re-anchor the propagated deadline on this process's clock (the
        # wire carries a relative budget; monotonic clocks don't agree
        # across processes).
        budget = meta.get("deadline_budget_s")
        deadline = (
            Deadline.after(budget) if budget is not None else Deadline.never()
        )
        if deadline.expired():
            # Arrived dead: doing the work wastes capacity on an answer
            # nobody is waiting for.
            raise exceptions.DeadlineExceededError(
                "request deadline expired before the replica started it"
            )
        if self._draining:
            raise exceptions.ReplicaDrainingError(self.replica_id)
        if self._ongoing >= self._admission_limit:
            # Replica-side admission control: local queue projects past
            # what the deployment config allows — shed fast instead of
            # queueing to death.
            self._shed += 1
            raise exceptions.RequestShedError(
                f"replica {self.replica_id} over admission limit "
                f"({self._ongoing} >= {self._admission_limit})"
            )
        # Chaos hooks (ISSUE 13): mid-request kill emulates a replica
        # dying while holding the request; the latency point emulates a
        # slow replica for hedging/SLO tests.
        try:
            chaos.failpoint("serve.replica.mid_request")
        except chaos.ChaosFault:
            os._exit(1)
        extra = chaos.latency_delay("serve.replica.request")
        if extra > 0:
            await asyncio.sleep(extra)
        self._ongoing += 1
        self._total += 1
        start = time.perf_counter()
        token = _request_context.set(meta)
        deadline_token = set_current_deadline(deadline)
        try:
            if self._is_function:
                target = self._callable
            else:
                target = getattr(self._callable, meta.get("method_name", "__call__"))
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            if inspect.isgenerator(result) or inspect.isasyncgen(result):
                # Streaming response (LLM token streams etc., reference:
                # generator deployments + StreamingResponse): register the
                # generator and hand back a stream marker; the caller pulls
                # chunks via stream_next() (batched per RPC). The ongoing
                # gauge stays raised until the stream finishes — a live
                # token stream IS an ongoing request for autoscaling.
                stream_id = self._open_stream(
                    result, model_id=meta.get("multiplexed_model_id", "")
                )
                self._ongoing += 1  # released by _finish_stream
                if meta.get("shape_key"):
                    self._warm_shapes.add(meta["shape_key"])
                return {"__serve_stream__": stream_id}
            # Warmth is recorded only on SUCCESS: a replica that keeps
            # failing a shape must not advertise it and pin the whole
            # shape's traffic (plus its retries) onto itself.
            if meta.get("shape_key"):
                self._warm_shapes.add(meta["shape_key"])
            return result
        finally:
            reset_current_deadline(deadline_token)
            _request_context.reset(token)
            self._ongoing -= 1
            self._latency_hist.observe(time.perf_counter() - start)

    # -- streaming ------------------------------------------------------
    STREAM_IDLE_TTL_S = 120.0

    def _open_stream(self, gen, model_id: str = "") -> str:
        from ray_tpu.dag.channels import LocalChannel

        stream_id = (
            f"stream-{self.replica_id}-{self._incarnation}"
            f"-{self._stream_counter}"
        )
        self._stream_counter += 1
        # The token stream rides an rtdag LocalChannel — the same-process
        # channel family of the compiled-dataflow plane (ISSUE 15); its
        # bounded ring is the decode-loop backpressure.
        chan = LocalChannel(maxsize=256, group="serve", label=stream_id)
        task = asyncio.get_running_loop().create_task(self._pump(gen, chan))
        # Pin the stream's multiplexed model (ISSUE 17 satellite 6): an
        # LRU swap must not checkpoint-evict a model whose stream is
        # still decoding — eviction defers until the last pin releases.
        if model_id:
            from ray_tpu.serve.multiplex import pin_model

            pin_model(model_id)
        self._streams[stream_id] = {
            "chan": chan, "task": task, "last_access": time.monotonic(),
            "model_id": model_id,
        }
        self._reap_idle_streams()
        return stream_id

    def _finish_stream(self, stream_id: str) -> None:
        entry = self._streams.pop(stream_id, None)
        if entry is not None:
            entry["task"].cancel()
            entry["chan"].close()
            if entry.get("model_id"):
                from ray_tpu.serve.multiplex import unpin_model

                unpin_model(entry["model_id"])
            self._ongoing -= 1

    def _reap_idle_streams(self) -> None:
        """Abandoned streams (client crashed / never iterated) must not pin
        the generator + channel + ongoing slot forever."""
        now = time.monotonic()
        for sid, entry in list(self._streams.items()):
            if now - entry["last_access"] > self.STREAM_IDLE_TTL_S:
                self._finish_stream(sid)

    async def _pump(self, gen, chan) -> None:
        """Drains the user generator into the stream channel. Sentinel
        dicts terminate: {'done': True} or {'error': repr}."""
        from ray_tpu.dag.channels import ChannelClosedError

        try:
            if inspect.isasyncgen(gen):
                async for item in gen:
                    await chan.put({"item": item})
            else:
                for item in gen:
                    await chan.put({"item": item})
                    await asyncio.sleep(0)  # let consumers interleave
            await chan.put({"done": True})
        except ChannelClosedError:
            return  # stream finished/cancelled under us: nothing to park
        except Exception as exc:
            try:
                await chan.put({"error": f"{type(exc).__name__}: {exc}"})
            except ChannelClosedError:
                return
        finally:
            # The generator body may hold device buffers; drop our ref
            # promptly rather than waiting for task GC.
            del gen

    async def stream_next(
        self, stream_id: str, max_items: int = 64, timeout_s: float = 30.0
    ) -> dict:
        """Pop at least one event (blocking up to timeout_s), then drain up
        to max_items without waiting — batching amortizes the per-chunk
        RPC (LocalChannel.pop_batch IS those semantics)."""
        entry = self._streams.get(stream_id)
        if entry is None:
            return {"items": [], "done": True, "error": "unknown stream"}
        entry["last_access"] = time.monotonic()
        events = await entry["chan"].pop_batch(max_items, timeout_s)
        if not events:
            entry["last_access"] = time.monotonic()
            return {"items": [], "done": False}
        items: list = []
        done = False
        error = None
        for event in events:
            if "item" in event:
                items.append(event["item"])
            else:
                done = True
                error = event.get("error")
                break
        if done:
            self._finish_stream(stream_id)
        else:
            entry["last_access"] = time.monotonic()
        out = {"items": items, "done": done}
        if error:
            out["error"] = error
        return out

    def stream_cancel(self, stream_id: str) -> str:
        self._finish_stream(stream_id)
        return "ok"

    # -- control plane --------------------------------------------------
    def reconfigure(self, user_config: Any) -> str:
        self._apply_reconfigure(user_config)
        return "ok"

    def _apply_reconfigure(self, user_config: Any) -> None:
        if not self._is_function and hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)

    async def check_health(self) -> str:
        # Periodic controller health checks double as the reaper tick for
        # abandoned streams (no reliance on further streaming traffic).
        self._reap_idle_streams()
        if not self._is_function and hasattr(self._callable, "check_health"):
            result = self._callable.check_health()
            if inspect.iscoroutine(result):
                await result
        # "draining" is a healthy state that must leave the routing set:
        # the controller sees it (e.g. after a SIGTERM the controller
        # didn't initiate) and starts a replacement + excludes this
        # replica from membership.
        return "draining" if self._draining else "ok"

    def get_metrics(self) -> dict:
        from ray_tpu._private.worker_proc import _peak_rss_bytes
        from ray_tpu.serve import batching

        lat = self._latency_hist.snapshot()
        batch_stats = batching.queue_stats()
        out = {
            "replica_id": self.replica_id,
            "ongoing": self._ongoing,
            "total": self._total,
            "shed": self._shed,
            "draining": self._draining,
            "p50_ms": lat["p50_ms"],
            "p95_ms": lat["p95_ms"],
            "p99_ms": lat["p99_ms"],
            # Batching occupancy (ISSUE 8): how full the padded TPU
            # batches actually are, plus requests parked waiting for a
            # flush.
            "queue_depth": batch_stats["queue_depth"],
            "batch_occupancy": batch_stats["batch_occupancy"],
            "avg_batch_occupancy": batch_stats["avg_occupancy"],
            # Resource telemetry (ISSUE 5): replica memory footprint so
            # autoscaling/status surfaces see per-replica RSS alongside
            # latency.
            "rss_bytes": _peak_rss_bytes(),
        }
        # Continuous-batching stats (ISSUE 17 satellite 2): the decode
        # engine's per-iteration slot occupancy replaces the batch-
        # boundary occupancy for deployments hosting one — the dashboard
        # fields track the running batch, not the last flushed one.
        stats_fn = getattr(self._callable, "serve_llm_stats", None)
        if callable(stats_fn):
            try:
                llm_stats = stats_fn()
                out["serve_llm"] = llm_stats
                out["queue_depth"] += llm_stats.get("queue_depth", 0)
                out["batch_occupancy"] = llm_stats.get(
                    "slot_occupancy_frac"
                )
            except Exception:  # rtlint: disable=swallowed-exception - stats merge must never fail a metrics poll
                pass
        # Push the occupancy gauges on the controller's metric-poll tick:
        # the poll cadence IS the gauge cadence, no extra timer needed.
        try:
            from ray_tpu.util import metrics as metrics_mod

            metrics_mod.set_serve_replica_gauge(
                "ongoing_requests", self.deployment_name, self.replica_id,
                self._ongoing,
            )
            metrics_mod.set_serve_replica_gauge(
                "queue_depth", self.deployment_name, self.replica_id,
                batch_stats["queue_depth"],
            )
            if batch_stats["batch_occupancy"] is not None:
                metrics_mod.set_serve_replica_gauge(
                    "batch_occupancy", self.deployment_name,
                    self.replica_id, batch_stats["batch_occupancy"],
                )
        except Exception:  # rtlint: disable=swallowed-exception - metric export must never fail a request
            pass
        return out

    def get_num_ongoing(self) -> int:
        return self._ongoing

    def get_node_id(self) -> str:
        return os.environ.get("RAYTPU_NODE_ID", "")

    def get_load(self) -> dict:
        """Autoscaler input: in-flight requests plus queued-but-unstarted
        batching depth (the part `ongoing` alone hides). Decode-engine
        replicas also report KV-pool headroom (ISSUE 17 tentpole d) —
        `ongoing` already covers engine slots (each occupied slot IS an
        in-flight request), so only the memory signal merges in."""
        from ray_tpu.serve import batching

        load = {
            "ongoing": self._ongoing,
            "queue_depth": batching.queue_stats()["queue_depth"],
            "draining": self._draining,
        }
        load_fn = getattr(self._callable, "serve_llm_load", None)
        if callable(load_fn):
            try:
                load["kv_free_frac"] = load_fn().get("kv_free_frac")
            except Exception:  # rtlint: disable=swallowed-exception - load merge must never fail an autoscaler poll
                pass
        return load

    def get_warm_shapes(self) -> list:
        """Shape keys whose XLA programs this replica has already
        compiled (explicit request shape_keys + batching buckets) — the
        router prefers warm replicas to avoid compile-latency cliffs
        (SURVEY §3.4 'compile-cache-aware stickiness')."""
        from ray_tpu.serve import batching

        return sorted(self._warm_shapes | batching.warm_shapes())

    def _on_sigterm(self, signum, frame) -> None:
        logger.info(
            "replica %s received SIGTERM: draining", self.replica_id
        )
        self._draining = True

    async def drain(self, checkpoint: bool = True) -> dict:
        """Enter the drain lifecycle: stop accepting new requests (the
        membership update pulls this replica from routers; stragglers get
        ReplicaDrainingError), checkpoint multiplexed models, and report
        in-flight work so the controller knows when the kill is clean."""
        first = not self._draining
        self._draining = True
        checkpointed = 0
        if checkpoint and first:
            from ray_tpu.serve.multiplex import checkpoint_loaded_models

            checkpointed = await checkpoint_loaded_models()
        return {
            "draining": True,
            "ongoing": self._ongoing,
            "streams": len(self._streams),
            "checkpointed_models": checkpointed,
        }

    async def prepare_to_drain(self) -> str:
        await self.drain()
        return "ok"

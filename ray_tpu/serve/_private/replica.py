"""Replica — the actor hosting one copy of a deployment.

Role-equivalent of python/ray/serve/_private/replica.py ::
UserCallableWrapper (SURVEY §2.6): constructs the user class (resolving
DeploymentHandle placeholders for model composition), serves requests with
an ongoing-request gauge (max_ongoing_requests backpressure lives in the
router), supports reconfigure(user_config), health checks, and multiplexed
model loading.
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import time
from typing import Any

_request_context: contextvars.ContextVar = contextvars.ContextVar(
    "serve_request_context", default=None
)


def get_current_request_metadata():
    return _request_context.get()


class Replica:
    """Runs inside a ray_tpu actor with max_concurrency > 1."""

    def __init__(
        self,
        replica_id: str,
        deployment_name: str,
        cls_or_fn: Any,
        init_args: tuple,
        init_kwargs: dict,
        user_config: Any,
        version: str,
    ):
        from ray_tpu.serve.handle import _resolve_handle_placeholders

        self.replica_id = replica_id
        self.deployment_name = deployment_name
        self.version = version
        self._ongoing = 0
        self._total = 0
        self._latencies: list[float] = []
        init_args = _resolve_handle_placeholders(init_args)
        init_kwargs = _resolve_handle_placeholders(init_kwargs)
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = cls_or_fn
            self._is_function = True
        if user_config is not None:
            self._apply_reconfigure(user_config)

    # -- request path ---------------------------------------------------
    async def handle_request(self, meta: dict, args: tuple, kwargs: dict) -> Any:
        self._ongoing += 1
        self._total += 1
        start = time.perf_counter()
        token = _request_context.set(meta)
        try:
            if self._is_function:
                target = self._callable
            else:
                target = getattr(self._callable, meta.get("method_name", "__call__"))
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            _request_context.reset(token)
            self._ongoing -= 1
            self._latencies.append(time.perf_counter() - start)
            if len(self._latencies) > 1000:
                del self._latencies[:500]

    # -- control plane --------------------------------------------------
    def reconfigure(self, user_config: Any) -> str:
        self._apply_reconfigure(user_config)
        return "ok"

    def _apply_reconfigure(self, user_config: Any) -> None:
        if not self._is_function and hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)

    async def check_health(self) -> str:
        if not self._is_function and hasattr(self._callable, "check_health"):
            result = self._callable.check_health()
            if inspect.iscoroutine(result):
                await result
        return "ok"

    def get_metrics(self) -> dict:
        lat = sorted(self._latencies[-200:])
        return {
            "replica_id": self.replica_id,
            "ongoing": self._ongoing,
            "total": self._total,
            "p50_ms": 1e3 * lat[len(lat) // 2] if lat else 0.0,
            "p99_ms": 1e3 * lat[int(len(lat) * 0.99)] if lat else 0.0,
        }

    def get_num_ongoing(self) -> int:
        return self._ongoing

    def prepare_to_drain(self) -> str:
        return "ok"

"""DeploymentHandle + Router — client-side load-balanced calls.

Role-equivalents of python/ray/serve/handle.py :: DeploymentHandle /
DeploymentResponse and _private/router.py + replica_scheduler (SURVEY
§2.6): the handle keeps a router that tracks the deployment's live
replicas (refreshed from the controller), picks a replica by rendezvous-
hashing the request's affinity key over the live set with bounded-load
fallback (routing.HashRing, ISSUE 17 — replaces power-of-two-choices;
session/model-keyed traffic sticks to the replica holding its KV blocks
or LRU-loaded model, keyless traffic spreads uniformly by request id),
and returns futures (DeploymentResponse) that compose between
deployments.

Reliability layer (ISSUE 13): every call carries a Deadline created at
ingress; retries are budgeted by the deployment's RetryPolicy (full-jitter
backoff, bounded by the remaining deadline) instead of the old retry-once
handoff; optional hedging launches a second attempt at the route's
observed p95 and cancels the loser; a per-replica circuit breaker stops
routing to a flapping replica before it times out a queue of requests.
"""

from __future__ import annotations

import collections
import logging
import math
import threading
import time
import uuid
from typing import Any, Optional

import ray_tpu
from ray_tpu import exceptions
from ray_tpu.serve._private.common import (
    Deadline,
    RequestMetadata,
    RetryPolicy,
    current_deadline,
)
from ray_tpu.serve._private.routing import HashRing
from ray_tpu.util import tracing
from ray_tpu.util.metrics import (
    inc_serve_reliability,
    set_serve_breaker_state,
)

logger = logging.getLogger(__name__)

# get()-level failures that mean "the replica process is gone", as opposed
# to the request being slow or user code raising.
_REPLICA_DEATH_ERRORS = (
    exceptions.ActorDiedError,
    exceptions.ActorUnavailableError,
    exceptions.WorkerCrashedError,
)

# Replica-raised control-flow errors cross the actor wire wrapped in
# TaskError (type is not preserved, only the remote traceback). Each is
# raised with its class name in the message, so the traceback tail is an
# unambiguous marker.
_REMOTE_ERROR_KINDS = (
    "ReplicaDrainingError",
    "RequestShedError",
    "DeadlineExceededError",
)


def _remote_error_kind(exc: Exception) -> Optional[str]:
    if isinstance(exc, exceptions.TaskError):
        tb = exc.remote_traceback or ""
        for kind in _REMOTE_ERROR_KINDS:
            if kind in tb:
                return kind
    return None


class CircuitBreaker:
    """Per-replica breaker: consecutive failures open it; after a cooldown
    it half-opens (probe traffic allowed); one success closes it again.

    States: 0=closed, 1=half-open, 2=open (the rt_serve_breaker_state
    gauge uses the same encoding).
    """

    CLOSED, HALF_OPEN, OPEN = 0, 1, 2

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 5.0):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._lock = threading.Lock()

    def can_route(self) -> bool:
        with self._lock:
            if self.state == self.OPEN:
                if time.monotonic() - self._opened_at >= self.cooldown_s:
                    self.state = self.HALF_OPEN
                    return True
                return False
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self.state = self.CLOSED

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if (
                self.state == self.HALF_OPEN
                or self._failures >= self.failure_threshold
            ):
                self.state = self.OPEN
                self._opened_at = time.monotonic()


class _Attempt:
    """One in-flight dispatch of a request onto a specific replica. Tracks
    its router slot so every launched attempt releases exactly once."""

    __slots__ = ("replica", "ref", "launched_at", "released", "discarded")

    def __init__(self, replica: str, ref):
        self.replica = replica
        self.ref = ref
        self.launched_at = time.monotonic()
        self.released = False
        self.discarded = False


class DeploymentResponse:
    """Future for one deployment call; .result() blocks, passing the
    response into another handle call chains through the object store.

    Drives the retry/hedge state machine: replica deaths re-dispatch while
    the RetryPolicy budget and the request Deadline both have room; a
    timeout on a dead replica is a retriable death, a timeout on a live
    replica is a DeadlineExceededError.
    """

    def __init__(self, handle: "DeploymentHandle", router: "Router",
                 meta: RequestMetadata, args: tuple, kwargs: dict,
                 deadline: Deadline, policy: RetryPolicy,
                 first_attempt: _Attempt):
        self._handle = handle
        self._router = router
        self._meta = meta
        self._args = args
        self._kwargs = kwargs
        self._deadline = deadline
        self._policy = policy
        self._attempts: list[_Attempt] = [first_attempt]
        self._attempts_launched = 1
        self._drain_retries = 0
        self._hedged = False
        self._deployment = handle.deployment_name
        self._done = False

    # Winning replica (stream pulls route here). Before a winner is known
    # this is the primary attempt's replica.
    @property
    def _replica_name(self) -> str:
        for att in self._attempts:
            if not att.discarded:
                return att.replica
        return self._attempts[-1].replica if self._attempts else ""

    # -- public API -----------------------------------------------------
    def result(self, timeout: Optional[float] = None) -> Any:
        """Block for the call's value. ``timeout`` (when given) tightens
        the propagated deadline; it can never extend it."""
        deadline = self._deadline
        if timeout is not None:
            tightened = Deadline.after(timeout)
            if tightened.at_monotonic < deadline.at_monotonic:
                deadline = tightened
        return self._await_result(deadline)

    # -- state machine --------------------------------------------------
    def _live_attempts(self) -> list[_Attempt]:
        return [a for a in self._attempts if not a.discarded]

    def _await_result(self, deadline: Deadline) -> Any:
        from ray_tpu.util.backoff import Backoff

        pol = self._policy
        backoff = Backoff(
            initial_backoff_s=pol.initial_backoff_s,
            max_backoff_s=pol.max_backoff_s,
        )
        hedge_after = self._hedge_delay() if pol.hedge else None
        while True:
            live = self._live_attempts()
            if not live:
                live = [self._relaunch_or_raise(deadline, backoff, None)]
            remaining = deadline.remaining()
            if remaining <= 0.0:
                return self._on_deadline_expired(deadline)
            waits = [remaining]
            hedge_due = False
            if (
                hedge_after is not None
                and not self._hedged
                and len(live) == 1
                and self._attempts_launched < max(2, pol.max_attempts)
            ):
                until_hedge = (
                    live[0].launched_at + hedge_after - time.monotonic()
                )
                if until_hedge <= 0.0:
                    hedge_due = True
                else:
                    waits.append(until_hedge)
            if hedge_due:
                self._launch_hedge(deadline)
                live = self._live_attempts()
            ready, _ = ray_tpu.wait(
                [a.ref for a in live],
                num_returns=1,
                timeout=max(0.01, min(waits)),
            )
            if not ready:
                continue
            att = next(a for a in live if a.ref == ready[0])
            try:
                value = ray_tpu.get(att.ref, timeout=deadline.remaining(cap=5.0) + 1.0)
            except _REPLICA_DEATH_ERRORS as exc:
                self._on_attempt_death(att, exc)
                if not self._live_attempts():
                    self._relaunch_or_raise(deadline, backoff, exc)
                continue
            except exceptions.GetTimeoutError:
                # wait() said ready but the fetch stalled — treat like the
                # deadline path on the next loop iteration.
                continue
            except Exception as exc:
                kind = _remote_error_kind(exc)
                if kind == "ReplicaDrainingError":
                    self._on_attempt_draining(att, deadline, exc)
                    continue
                self._finish_all(winner=None)
                if kind == "RequestShedError":
                    # The replica's Retry-After estimate rides the remote
                    # message ("retry_after_s=X" — e.g. the decode
                    # engine's slot-free projection); recover it so the
                    # proxy's 503 hint reflects the shedder's estimate
                    # instead of a flat 1s.
                    import re as _re

                    m = _re.search(r"retry_after_s=([0-9.]+)", str(exc))
                    raise exceptions.RequestShedError(
                        f"replica of {self._deployment!r} shed the request",
                        retry_after_s=float(m.group(1)) if m else 1.0,
                    ) from exc
                if kind == "DeadlineExceededError":
                    inc_serve_reliability(
                        "deadline_exceeded", deployment=self._deployment
                    )
                    raise exceptions.DeadlineExceededError(
                        f"deadline expired inside {self._deployment!r}"
                    ) from exc
                raise
            # Success on `att`.
            self._router.breaker(att.replica).record_success()
            self._finish_all(winner=att)
            if isinstance(value, dict) and "__serve_stream__" in value:
                # Streaming deployment (generator handler): hand back an
                # iterator that pulls batched chunks from the replica. The
                # router's ongoing slot stays held until the stream ends —
                # a live token stream IS an ongoing request.
                return ResponseStream(
                    self, value["__serve_stream__"], att.replica, deadline
                )
            self._release(att)
            self._router.note_latency(time.monotonic() - att.launched_at)
            self._done = True
            return value

    def _hedge_delay(self) -> float:
        if self._policy.hedge_after_s is not None:
            return max(0.0, self._policy.hedge_after_s)
        return self._router.observed_p95()

    def _launch_hedge(self, deadline: Deadline) -> None:
        self._hedged = True
        primary = {a.replica for a in self._live_attempts()}
        try:
            att = self._handle._launch_attempt(
                self._router, self._meta, self._args, self._kwargs,
                deadline, exclude=primary, attempt=self._attempts_launched,
            )
        except Exception:  # rtlint: disable=swallowed-exception - hedge is an optimization: no spare replica means no hedge, the primary attempt proceeds
            inc_serve_reliability(
                "hedges", deployment=self._deployment, outcome="skipped"
            )
            return
        self._attempts.append(att)
        self._attempts_launched += 1
        inc_serve_reliability(
            "hedges", deployment=self._deployment, outcome="launched"
        )

    def _relaunch_or_raise(self, deadline: Deadline, backoff,
                           cause: Optional[Exception]) -> _Attempt:
        """Dispatch a replacement attempt under the retry budget, or raise
        the terminal error for this request."""
        pol = self._policy
        if (
            self._attempts_launched >= max(1, pol.max_attempts)
            or deadline.expired()
        ):
            last = self._attempts[-1] if self._attempts else None
            raise exceptions.ReplicaDiedError(
                self._deployment,
                last.replica if last else "<none>",
                f"retry budget exhausted after "
                f"{self._attempts_launched} attempt(s)",
            ) from cause
        delay = backoff.next_delay(cap=deadline.remaining())
        if delay > 0:
            time.sleep(delay)
        dead = {a.replica for a in self._attempts}
        try:
            att = self._handle._launch_attempt(
                self._router, self._meta, self._args, self._kwargs,
                deadline, exclude=dead, attempt=self._attempts_launched,
            )
        except Exception as exc:
            last = self._attempts[-1] if self._attempts else None
            raise exceptions.ReplicaDiedError(
                self._deployment,
                last.replica if last else "<none>",
                f"retry dispatch failed: {exc}",
            ) from (cause or exc)
        self._attempts.append(att)
        self._attempts_launched += 1
        inc_serve_reliability(
            "retries", deployment=self._deployment, reason="replica_death"
        )
        return att

    def _on_attempt_death(self, att: _Attempt, exc: Exception) -> None:
        self._discard(att)
        self._router.breaker(att.replica).record_failure()
        self._router.report_breaker(att.replica)
        self._router.drop_replica(att.replica)

    def _on_attempt_draining(self, att: _Attempt, deadline: Deadline,
                             exc: Exception) -> None:
        """Draining is deliberate (oom_risk / SIGTERM / scale-down): move
        to another replica without charging the breaker or retry budget,
        but bound the bounce count so a fully-draining fleet terminates."""
        self._discard(att)
        self._router.drop_replica(att.replica)
        self._drain_retries += 1
        if self._drain_retries > 8 or deadline.expired():
            self._finish_all(winner=None)
            raise exceptions.ReplicaDrainingError(att.replica) from exc
        if self._live_attempts():
            return
        try:
            fresh = self._handle._launch_attempt(
                self._router, self._meta, self._args, self._kwargs,
                deadline, exclude={a.replica for a in self._attempts},
                attempt=self._attempts_launched,
            )
        except Exception:
            self._finish_all(winner=None)
            raise exceptions.ReplicaDrainingError(att.replica) from exc
        self._attempts.append(fresh)
        self._attempts_launched += 1
        inc_serve_reliability(
            "retries", deployment=self._deployment, reason="draining"
        )

    def _on_deadline_expired(self, deadline: Deadline) -> Any:
        """Budget ran out with attempts still in flight. A timeout on a
        DEAD replica is a lost request, not a slow one — probe liveness
        (bounded by the configured probe timeout, not a hardcoded 5s)
        before surfacing the deadline error."""
        live = self._live_attempts()
        primary = live[0] if live else None
        self._finish_all(winner=None)
        inc_serve_reliability(
            "deadline_exceeded", deployment=self._deployment
        )
        if primary is not None and not self._replica_alive(primary.replica):
            raise exceptions.ReplicaDiedError(
                self._deployment, primary.replica,
                "replica died and the deadline expired before a retry "
                "could be dispatched",
            )
        raise exceptions.DeadlineExceededError(
            f"deadline expired waiting on {self._deployment!r}"
        )

    def _replica_alive(self, replica_name: str) -> bool:
        try:
            handle = self._router._replica_handle(replica_name)
            ray_tpu.get(
                handle.check_health.remote(),
                timeout=self._router.probe_timeout(),
            )
            return True
        except Exception:  # rtlint: disable=swallowed-exception - health probe: any failure counts as dead
            return False

    # -- slot bookkeeping -----------------------------------------------
    def _release(self, att: _Attempt) -> None:
        if not att.released:
            att.released = True
            self._router.on_request_done(att.replica)

    def _discard(self, att: _Attempt) -> None:
        att.discarded = True
        self._release(att)

    def _finish_all(self, winner: Optional[_Attempt]) -> None:
        """Settle every losing attempt: cancel best-effort, release its
        router slot. The winner's slot stays held (streams keep it until
        exhaustion; unary callers release right after)."""
        for att in self._attempts:
            if att is winner or att.discarded:
                continue
            att.discarded = True
            try:
                ray_tpu.cancel(att.ref)
            except Exception:  # rtlint: disable=swallowed-exception - loser cancel is best-effort; the replica's stream reaper collects leftovers
                pass
            self._release(att)
            if self._hedged:
                inc_serve_reliability(
                    "hedges", deployment=self._deployment, outcome="lost"
                )

    def _mark_done(self):
        """Release the winning attempt's slot (stream end / composition)."""
        if not self._done:
            self._done = True
            for att in self._attempts:
                self._release(att)

    def _to_object_ref(self):
        # Composed calls hand the ref downstream and never call
        # .result(); release the router's ongoing slots now or the
        # replica's count leaks permanently (router would declare
        # 'no available replica' after max_ongoing composed calls).
        live = self._live_attempts()
        ref = live[0].ref if live else self._attempts[-1].ref
        self._mark_done()
        return ref


class ResponseStream:
    """Iterator over a streaming deployment response (token streams).

    Pulls batched chunks via the replica's stream_next actor method;
    releases the router's ongoing slot when the stream finishes. Every
    pull timeout derives from the request's propagated Deadline.
    Role-equivalent of the reference's DeploymentResponseGenerator.
    """

    def __init__(self, response: "DeploymentResponse", stream_id: str,
                 replica_name: str | None = None,
                 deadline: Deadline | None = None):
        self._response = response
        self._stream_id = stream_id
        self._replica_name = replica_name or response._replica_name
        self._deadline = deadline or response._deadline
        self._buffer: list = []
        self._done = False
        self._error: str | None = None

    def __iter__(self):
        return self

    def _exhausted(self):
        # Buffered items always drain before a trailing error surfaces.
        if self._error is not None:
            error, self._error = self._error, None
            raise RuntimeError(f"streaming deployment failed: {error}")
        raise StopIteration

    def _fill(self) -> None:
        """Pull chunks from the replica until the buffer is non-empty or
        the stream ends. Each pull is bounded by the remaining request
        deadline (no more hardcoded `timeout + 30` slack)."""
        router = self._response._router
        replica = router._replica_handle(self._replica_name)
        while not self._buffer and not self._done:
            if self._deadline.expired():
                self.cancel()
                raise exceptions.DeadlineExceededError(
                    "stream stalled past the request deadline"
                )
            chunk = ray_tpu.get(
                replica.stream_next.remote(self._stream_id),
                timeout=max(0.05, self._deadline.remaining()),
            )
            self._buffer.extend(chunk.get("items", []))
            if chunk.get("done"):
                self._done = True
                self._error = chunk.get("error")
                self._response._mark_done()

    def __next__(self):
        if self._buffer:
            return self._buffer.pop(0)
        if self._done:
            self._exhausted()
        self._fill()
        if self._buffer:
            return self._buffer.pop(0)
        self._exhausted()

    def next_batch(self) -> list:
        """All currently-buffered items (pulling one replica chunk when
        empty); [] means end-of-stream. One blocking call per replica RPC —
        batch consumers (the HTTP proxy) avoid a thread hop per item."""
        if not self._buffer and not self._done:
            self._fill()
        if self._buffer:
            batch, self._buffer = self._buffer, []
            return batch
        if self._error is not None:
            self._exhausted()
        return []

    def cancel(self) -> None:
        if not self._done:
            self._done = True
            router = self._response._router
            try:
                replica = router._replica_handle(self._replica_name)
                ray_tpu.get(
                    replica.stream_cancel.remote(self._stream_id),
                    timeout=max(
                        router.probe_timeout(),
                        self._deadline.remaining(cap=10.0),
                    ),
                )
            except Exception as exc:
                # Cleanup failure is survivable (the replica's stream
                # reaper collects leftovers) but never silent: it leaks a
                # server-side buffer until then (PR-9 swallowed-exception
                # rule).
                logger.debug(
                    "stream_cancel for stream %s on %s failed: %s",
                    self._stream_id, self._replica_name, exc,
                )
                inc_serve_reliability(
                    "stream_cancel_failures",
                    deployment=self._response._deployment,
                )
            self._response._mark_done()


class Router:
    """Hash-ring replica choice with cached membership + local queue
    counts (rendezvous hashing, bounded-load fallback — ISSUE 17)."""

    # Bounded-load factor: a key's preferred replica is skipped once its
    # ongoing count exceeds BOUNDED_LOAD_FACTOR x the fleet average —
    # classic consistent-hashing-with-bounded-loads, so one hot session
    # cannot melt a single replica while the rest idle.
    BOUNDED_LOAD_FACTOR = 1.25

    REFRESH_INTERVAL_S = 1.0
    # Hedge delay fallback until enough latency samples exist.
    DEFAULT_P95_S = 1.0

    def __init__(self, deployment: str, app_name: str):
        self.deployment = deployment
        self.app_name = app_name
        self._qualified = f"{app_name}_{deployment}"
        self._replicas: list[str] = []  # actor names
        self._handles: dict[str, Any] = {}
        self._ongoing: dict[str, int] = {}
        # Replicas observed dead, banned until the controller's membership
        # catches up — _refresh would otherwise re-add the corpse from the
        # stale snapshot and the death-retry path would re-pick it.
        self._banned: dict[str, float] = {}
        self._max_ongoing = 100
        # Deployment policy subset published with the membership snapshot
        # (timeouts, retry policy, admission allowance) — replaces the old
        # scattered hardcoded constants.
        self._policy: dict = {}
        self._last_refresh = 0.0
        self._lock = threading.Lock()
        # Per-replica circuit breakers (ISSUE 13): consecutive dispatch/
        # completion failures open the breaker and take the replica out of
        # the candidate set until its cooldown half-opens it.
        self._breakers: dict[str, CircuitBreaker] = {}
        # Completed-request latency reservoir for the hedge trigger.
        self._latencies: collections.deque = collections.deque(maxlen=128)
        # Compile-cache-aware stickiness (SURVEY §3.4): per-replica warm
        # shape keys, polled lazily once any caller routes by shape_key.
        self._warm: dict[str, set] = {}
        self._warm_ts = 0.0
        # Affinity ring (ISSUE 17): rendezvous hashing over the live
        # replica set; membership updates ride _refresh.
        self._ring = HashRing()

    # -- policy ---------------------------------------------------------
    def policy(self) -> dict:
        with self._lock:
            return dict(self._policy)

    def retry_policy(self) -> RetryPolicy:
        return RetryPolicy.from_dict(self.policy().get("retry_policy", {}))

    def request_timeout_s(self) -> float:
        return float(self.policy().get("request_timeout_s", 60.0))

    def probe_timeout(self) -> float:
        return float(self.policy().get("health_probe_timeout_s", 5.0))

    def breaker(self, actor_name: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(actor_name)
            if br is None:
                br = self._breakers[actor_name] = CircuitBreaker()
            return br

    def report_breaker(self, actor_name: str) -> None:
        br = self.breaker(actor_name)
        set_serve_breaker_state(self._qualified, actor_name, br.state)

    def note_latency(self, seconds: float) -> None:
        self._latencies.append(seconds)

    def observed_p95(self) -> float:
        """Route-local p95 of completed request latencies; seeds the hedge
        trigger when RetryPolicy.hedge_after_s is unset."""
        samples = sorted(self._latencies)
        if len(samples) < 8:
            return self.DEFAULT_P95_S
        return samples[min(len(samples) - 1, int(0.95 * len(samples)))]

    # -- membership -----------------------------------------------------
    def _refresh(self, force: bool = False) -> None:
        """Membership comes from the process-wide long-poll subscriber
        (push, no RPC); force=True short-circuits with a direct snapshot
        fetch (scale-from-zero spin)."""
        from ray_tpu.serve._private.long_poll import get_subscriber

        subscriber = get_subscriber()
        if force:
            subscriber.force_refresh()
        info = subscriber.get_replicas(self._qualified)
        with self._lock:
            self._last_refresh = time.monotonic()
            now = time.monotonic()
            self._banned = {
                name: until
                for name, until in self._banned.items()
                if until > now
            }
            self._replicas = [
                name for name in info["actor_names"]
                if name not in self._banned
            ]
            self._max_ongoing = info.get("max_ongoing_requests", 100)
            self._policy = info.get("policy", self._policy)
            for name in self._replicas:
                self._ongoing.setdefault(name, 0)

    def _replica_handle(self, actor_name: str):
        handle = self._handles.get(actor_name)
        if handle is None:
            handle = ray_tpu.get_actor(actor_name)
            self._handles[actor_name] = handle
        return handle

    def _refresh_warm(self, candidates: list) -> None:
        """Poll per-replica warm shape sets (2s cadence): a replica that
        has compiled a bucket/shape reports it; the router then prefers
        warm replicas for same-shape traffic so autoscaling events don't
        turn into compile-latency cliffs (SURVEY §3.4)."""
        with self._lock:
            # check-and-set under the lock: concurrent callers must not
            # stampede duplicate warm polls
            if time.monotonic() - self._warm_ts < 2.0:
                return
            self._warm_ts = time.monotonic()
        import ray_tpu

        # Fan out, then collect under ONE short total budget: a hung
        # replica must not stall the request path for 5s x N.
        refs = {}
        for name in candidates:
            try:
                refs[name] = self._replica_handle(
                    name
                ).get_warm_shapes.remote()
            except Exception:  # rtlint: disable=swallowed-exception - dead replica: the collect loop below skips it
                pass
        deadline = time.monotonic() + 2.0
        updates: dict[str, set | None] = {}
        for name in candidates:
            ref = refs.get(name)
            if ref is None:
                updates[name] = None
                continue
            try:
                remaining = max(0.05, deadline - time.monotonic())
                updates[name] = set(ray_tpu.get(ref, timeout=remaining))
            except Exception:
                updates[name] = None
        with self._lock:
            for name, warm in updates.items():
                if warm is None:
                    self._warm.pop(name, None)
                else:
                    self._warm[name] = warm

    def choose_replica(self, shape_key: str | None = None,
                       deadline: Deadline | None = None,
                       exclude: set | frozenset = frozenset(),
                       affinity_key: str | None = None) -> str:
        """Pick a replica and take an ongoing slot on it. Selection is
        rendezvous hashing on ``affinity_key`` (session id, model id,
        shape key, or the request id for keyless spread) with bounded-
        load fallback. The wait for capacity/membership is bounded by
        the request's Deadline; ``exclude`` supports hedging and death
        retries."""
        deadline = deadline or Deadline.after(self.request_timeout_s())
        # Keyless requests spread uniformly: a one-shot random key gives
        # HRW the same distribution as random choice (kept stable across
        # this call's wait loop so bounded load doesn't thrash the pick).
        key = affinity_key or shape_key or uuid.uuid4().hex
        while True:
            self._refresh()
            with self._lock:
                candidates = [
                    c for c in self._replicas if c not in exclude
                ]
            # Breaker gate: flapping replicas drop out of the candidate
            # set; when EVERY candidate's breaker is open, fall through
            # with the full set (half-open probes beat a guaranteed error).
            routable = [c for c in candidates if self.breaker(c).can_route()]
            if routable:
                candidates = routable
            if candidates and shape_key:
                self._refresh_warm(candidates)
                warm = [
                    c for c in candidates
                    if shape_key in self._warm.get(c, ())
                ]
                # Prefer warm replicas unless they are saturated — a cold
                # compile beats unbounded queueing behind the warm one.
                warm_free = [
                    c for c in warm
                    if self._ongoing.get(c, 0) < self._max_ongoing
                ]
                if warm_free:
                    candidates = warm_free
            if candidates:
                with self._lock:
                    ongoing = dict(self._ongoing)
                # Bounded load: the key's preferred replica is skipped
                # once it is BOUNDED_LOAD_FACTOR past the fleet average
                # (and always at the hard max_ongoing cap).
                total = sum(ongoing.get(c, 0) for c in candidates)
                avg_bound = math.ceil(
                    self.BOUNDED_LOAD_FACTOR
                    * (total + 1) / max(1, len(candidates))
                )
                self._ring.update(candidates)
                pick = self._ring.pick(
                    key,
                    load=ongoing,
                    max_load=min(self._max_ongoing, max(1, avg_bound)),
                )
                if pick and ongoing.get(pick, 0) < self._max_ongoing:
                    with self._lock:
                        self._ongoing[pick] = self._ongoing.get(pick, 0) + 1
                    return pick
            if deadline.expired():
                raise RuntimeError(
                    f"no available replica for {self._qualified} "
                    f"(backpressure or scale-to-zero)"
                )
            time.sleep(min(0.05, max(0.005, deadline.remaining())))
            self._refresh(force=True)

    def on_request_done(self, actor_name: str) -> None:
        with self._lock:
            if actor_name in self._ongoing and self._ongoing[actor_name] > 0:
                self._ongoing[actor_name] -= 1

    def drop_replica(self, actor_name: str) -> None:
        with self._lock:
            self._replicas = [r for r in self._replicas if r != actor_name]
            self._handles.pop(actor_name, None)
            self._banned[actor_name] = time.monotonic() + 10.0


class DeploymentHandle:
    def __init__(self, deployment: str, app_name: str = "default"):
        self.deployment_name = deployment
        self.app_name = app_name
        self._router: Optional[Router] = None
        self._method_name = "__call__"
        self._model_id = ""
        self._shape_key = ""
        self._session_id = ""

    def _get_router(self) -> Router:
        if self._router is None:
            self._router = Router(self.deployment_name, self.app_name)
        return self._router

    def options(self, *, method_name: str | None = None,
                multiplexed_model_id: str | None = None,
                shape_key: str | None = None,
                session_id: str | None = None) -> "DeploymentHandle":
        """shape_key: opaque label of the request's compiled shape
        (sequence-length bucket, resolution, ...). Requests with the same
        key stick to replicas that already compiled it (§3.4).
        session_id: affinity key for the hash ring (ISSUE 17) — a
        session's requests land on the replica holding its KV blocks."""
        clone = DeploymentHandle(self.deployment_name, self.app_name)
        # Share ONE router across option clones (materialize it now: a
        # None copied here would fork load counts and warm caches later).
        clone._router = self._get_router()
        clone._method_name = method_name or self._method_name
        clone._model_id = multiplexed_model_id or self._model_id
        clone._shape_key = shape_key or self._shape_key
        clone._session_id = session_id or self._session_id
        return clone

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        router = self._get_router()
        # Pull membership/policy BEFORE seeding the deadline: a fresh
        # router has no policy yet and would price the budget off the
        # 60s default instead of the deployment's request_timeout_s.
        router._refresh()
        if not router.policy():
            router._refresh(force=True)
        # The ambient deadline (set by the proxy from the ingress header,
        # or by an enclosing replica call) wins; otherwise this call is
        # the ingress and seeds one from deployment config.
        deadline = current_deadline() or Deadline.after(
            router.request_timeout_s()
        )
        policy = router.retry_policy()
        meta = RequestMetadata(
            method_name=self._method_name,
            multiplexed_model_id=self._model_id,
            session_id=self._session_id,
        )
        # Compose: upstream DeploymentResponses pass as object refs so the
        # downstream replica reads the value without driver round-trips.
        args = tuple(
            a._to_object_ref() if isinstance(a, DeploymentResponse) else a
            for a in args
        )
        from ray_tpu.util.backoff import Backoff

        backoff = Backoff(
            initial_backoff_s=policy.initial_backoff_s,
            max_backoff_s=policy.max_backoff_s,
        )
        attempts = 0
        last_exc: Exception | None = None
        # Dispatch the FIRST attempt under the same budget as re-dispatch:
        # a replica dying between refresh and call costs one attempt.
        while True:
            try:
                first = self._launch_attempt(
                    router, meta, args, kwargs, deadline, attempt=attempts,
                )
                break
            except Exception as exc:
                attempts += 1
                last_exc = exc
                if attempts >= max(1, policy.max_attempts) or deadline.expired():
                    raise RuntimeError(
                        f"could not dispatch to {self.deployment_name}: "
                        f"{last_exc}"
                    ) from last_exc
                time.sleep(backoff.next_delay(cap=deadline.remaining()))
        return DeploymentResponse(
            self, router, meta, args, kwargs, deadline, policy, first,
        )

    def _launch_attempt(self, router: Router, meta: RequestMetadata,
                        args: tuple, kwargs: dict, deadline: Deadline,
                        exclude: set | frozenset = frozenset(),
                        attempt: int = 0) -> _Attempt:
        """One dispatch onto a chosen replica; takes (and on failure
        releases) the replica's ongoing slot."""
        # Affinity precedence: explicit session > multiplexed model (the
        # replica holding the LRU-loaded model) > compiled shape.
        affinity = (
            self._session_id or meta.multiplexed_model_id
            or self._shape_key or None
        )
        replica_name = router.choose_replica(
            shape_key=self._shape_key or None,
            deadline=deadline,
            exclude=exclude,
            affinity_key=affinity,
        )
        try:
            replica = router._replica_handle(replica_name)
        except Exception:  # name already unregistered: replica is dead
            router.on_request_done(replica_name)
            router.drop_replica(replica_name)
            raise
        try:
            ref = replica.handle_request.remote(
                {
                    "request_id": meta.request_id,
                    "method_name": meta.method_name,
                    "multiplexed_model_id": meta.multiplexed_model_id,
                    "shape_key": self._shape_key,
                    "session_id": meta.session_id,
                    # The remaining budget travels as a relative duration;
                    # the replica re-anchors it on its own clock.
                    "deadline_budget_s": deadline.budget(),
                    "attempt": attempt,
                    # Serve-level trace propagation: the proxy's (or any
                    # caller's) current span becomes the replica span's
                    # parent across the actor-call boundary.
                    "trace_ctx": tracing.inject(),
                },
                args,
                kwargs,
            )
        except Exception:
            router.on_request_done(replica_name)
            router.drop_replica(replica_name)
            raise
        return _Attempt(replica_name, ref)

    def __reduce__(self):
        return (_rebuild_handle, (self.deployment_name, self.app_name,
                                  self._method_name, self._model_id,
                                  self._shape_key, self._session_id))

    def __repr__(self):
        return f"DeploymentHandle({self.app_name}/{self.deployment_name})"


def _rebuild_handle(deployment, app_name, method_name, model_id,
                    shape_key="", session_id=""):
    handle = DeploymentHandle(deployment, app_name)
    handle._method_name = method_name
    handle._model_id = model_id
    handle._shape_key = shape_key
    handle._session_id = session_id
    return handle


class _HandlePlaceholder:
    """Marks a bound sub-deployment inside init args; replicas resolve it
    to a live DeploymentHandle at construction time."""

    def __init__(self, deployment: str, app_name: str):
        self.deployment = deployment
        self.app_name = app_name


def _resolve_handle_placeholders(obj: Any) -> Any:
    if isinstance(obj, _HandlePlaceholder):
        return DeploymentHandle(obj.deployment, obj.app_name)
    if isinstance(obj, tuple):
        return tuple(_resolve_handle_placeholders(x) for x in obj)
    if isinstance(obj, list):
        return [_resolve_handle_placeholders(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _resolve_handle_placeholders(v) for k, v in obj.items()}
    return obj

"""DeploymentHandle + Router — client-side load-balanced calls.

Role-equivalents of python/ray/serve/handle.py :: DeploymentHandle /
DeploymentResponse and _private/router.py + replica_scheduler/
pow_2_scheduler.py :: PowerOfTwoChoicesReplicaScheduler (SURVEY §2.6):
the handle keeps a router that tracks the deployment's live replicas
(refreshed from the controller), picks between two random replicas by
queue length (locally-tracked ongoing counts + max_ongoing_requests
backpressure), and returns futures (DeploymentResponse) that compose
between deployments.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Optional

import ray_tpu
from ray_tpu import exceptions
from ray_tpu.serve._private.common import CONTROLLER_NAME, RequestMetadata
from ray_tpu.util import tracing

# get()-level failures that mean "the replica process is gone", as opposed
# to the request being slow or user code raising.
_REPLICA_DEATH_ERRORS = (
    exceptions.ActorDiedError,
    exceptions.ActorUnavailableError,
    exceptions.WorkerCrashedError,
)


class DeploymentResponse:
    """Future for one deployment call; .result() blocks, passing the
    response into another handle call chains through the object store."""

    def __init__(self, ref, router: "Router", replica_name: str,
                 deployment: str = "", retry=None):
        self._ref = ref
        self._router = router
        self._replica_name = replica_name
        self._deployment = deployment
        # Zero-arg callable re-dispatching this request onto a healthy
        # replica (set by DeploymentHandle.remote; the retried response
        # carries retry=None so one request retries at most once).
        self._retry = retry
        self._done = False

    def result(self, timeout: Optional[float] = 60.0) -> Any:
        try:
            value = ray_tpu.get(self._ref, timeout=timeout)
        except _REPLICA_DEATH_ERRORS as exc:
            return self._on_replica_death(exc, timeout)
        except exceptions.GetTimeoutError as exc:
            # A timeout on a DEAD replica is a lost request, not a slow
            # one — probe liveness before surfacing a bare timeout.
            if self._replica_alive():
                self._mark_done()
                raise
            return self._on_replica_death(exc, timeout)
        except Exception:
            self._mark_done()
            raise
        if isinstance(value, dict) and "__serve_stream__" in value:
            # Streaming deployment (generator handler): hand back an
            # iterator that pulls batched chunks from the replica. The
            # router's ongoing slot stays held until the stream ends —
            # a live token stream IS an ongoing request.
            return ResponseStream(self, value["__serve_stream__"])
        self._mark_done()
        return value

    def _replica_alive(self) -> bool:
        try:
            handle = self._router._replica_handle(self._replica_name)
            ray_tpu.get(handle.check_health.remote(), timeout=5)
            return True
        except Exception:  # rtlint: disable=swallowed-exception - health probe: any failure counts as dead
            return False

    def _on_replica_death(self, exc: Exception, timeout) -> Any:
        """The backing replica died mid-call: drop it from the router,
        retry ONCE against a healthy replica, and if that is impossible
        surface a typed ReplicaDiedError instead of the raw actor error
        or a bare timeout."""
        self._mark_done()
        self._router.drop_replica(self._replica_name)
        if self._retry is not None:
            retry, self._retry = self._retry, None
            try:
                fresh = retry()
            except Exception as retry_exc:
                raise exceptions.ReplicaDiedError(
                    self._deployment, self._replica_name,
                    f"retry dispatch failed: {retry_exc}",
                ) from exc
            return fresh.result(timeout=timeout)
        raise exceptions.ReplicaDiedError(
            self._deployment, self._replica_name, str(exc)
        ) from exc

    def _mark_done(self):
        if not self._done:
            self._done = True
            self._router.on_request_done(self._replica_name)

    def _to_object_ref(self):
        # Composed calls hand the ref downstream and never call
        # .result(); release the router's ongoing slot now or the
        # replica's count leaks permanently (router would declare
        # 'no available replica' after max_ongoing composed calls).
        self._mark_done()
        return self._ref


class ResponseStream:
    """Iterator over a streaming deployment response (token streams).

    Pulls batched chunks via the replica's stream_next actor method;
    releases the router's ongoing slot when the stream finishes.
    Role-equivalent of the reference's DeploymentResponseGenerator.
    """

    def __init__(self, response: "DeploymentResponse", stream_id: str):
        self._response = response
        self._stream_id = stream_id
        self._buffer: list = []
        self._done = False
        self._error: str | None = None
        self._timeout_s = 60.0

    def __iter__(self):
        return self

    def _exhausted(self):
        # Buffered items always drain before a trailing error surfaces.
        if self._error is not None:
            error, self._error = self._error, None
            raise RuntimeError(f"streaming deployment failed: {error}")
        raise StopIteration

    def _fill(self) -> None:
        """Pull chunks from the replica until the buffer is non-empty or
        the stream ends."""
        router = self._response._router
        replica = router._replica_handle(self._response._replica_name)
        deadline = time.monotonic() + self._timeout_s
        while not self._buffer and not self._done:
            chunk = ray_tpu.get(
                replica.stream_next.remote(self._stream_id),
                timeout=self._timeout_s + 30,
            )
            self._buffer.extend(chunk.get("items", []))
            if chunk.get("done"):
                self._done = True
                self._error = chunk.get("error")
                self._response._mark_done()
            elif time.monotonic() > deadline and not self._buffer:
                self.cancel()
                raise TimeoutError("stream stalled")

    def __next__(self):
        if self._buffer:
            return self._buffer.pop(0)
        if self._done:
            self._exhausted()
        self._fill()
        if self._buffer:
            return self._buffer.pop(0)
        self._exhausted()

    def next_batch(self) -> list:
        """All currently-buffered items (pulling one replica chunk when
        empty); [] means end-of-stream. One blocking call per replica RPC —
        batch consumers (the HTTP proxy) avoid a thread hop per item."""
        if not self._buffer and not self._done:
            self._fill()
        if self._buffer:
            batch, self._buffer = self._buffer, []
            return batch
        if self._error is not None:
            self._exhausted()
        return []

    def cancel(self) -> None:
        if not self._done:
            self._done = True
            router = self._response._router
            try:
                replica = router._replica_handle(self._response._replica_name)
                ray_tpu.get(
                    replica.stream_cancel.remote(self._stream_id), timeout=30
                )
            except Exception:  # rtlint: disable=swallowed-exception - replica died; the stream is already torn down
                pass
            self._response._mark_done()


class Router:
    """Pow-2 replica choice with cached membership + local queue counts."""

    REFRESH_INTERVAL_S = 1.0

    def __init__(self, deployment: str, app_name: str):
        self.deployment = deployment
        self.app_name = app_name
        self._qualified = f"{app_name}_{deployment}"
        self._replicas: list[str] = []  # actor names
        self._handles: dict[str, Any] = {}
        self._ongoing: dict[str, int] = {}
        # Replicas observed dead, banned until the controller's membership
        # catches up — _refresh would otherwise re-add the corpse from the
        # stale snapshot and the death-retry path would re-pick it.
        self._banned: dict[str, float] = {}
        self._max_ongoing = 100
        self._last_refresh = 0.0
        self._lock = threading.Lock()
        # Compile-cache-aware stickiness (SURVEY §3.4): per-replica warm
        # shape keys, polled lazily once any caller routes by shape_key.
        self._warm: dict[str, set] = {}
        self._warm_ts = 0.0

    def _refresh(self, force: bool = False) -> None:
        """Membership comes from the process-wide long-poll subscriber
        (push, no RPC); force=True short-circuits with a direct snapshot
        fetch (scale-from-zero spin)."""
        from ray_tpu.serve._private.long_poll import get_subscriber

        subscriber = get_subscriber()
        if force:
            subscriber.force_refresh()
        info = subscriber.get_replicas(self._qualified)
        with self._lock:
            self._last_refresh = time.monotonic()
            now = time.monotonic()
            self._banned = {
                name: until
                for name, until in self._banned.items()
                if until > now
            }
            self._replicas = [
                name for name in info["actor_names"]
                if name not in self._banned
            ]
            self._max_ongoing = info.get("max_ongoing_requests", 100)
            for name in self._replicas:
                self._ongoing.setdefault(name, 0)

    def _replica_handle(self, actor_name: str):
        handle = self._handles.get(actor_name)
        if handle is None:
            handle = ray_tpu.get_actor(actor_name)
            self._handles[actor_name] = handle
        return handle

    def _refresh_warm(self, candidates: list) -> None:
        """Poll per-replica warm shape sets (2s cadence): a replica that
        has compiled a bucket/shape reports it; the router then prefers
        warm replicas for same-shape traffic so autoscaling events don't
        turn into compile-latency cliffs (SURVEY §3.4)."""
        with self._lock:
            # check-and-set under the lock: concurrent callers must not
            # stampede duplicate warm polls
            if time.monotonic() - self._warm_ts < 2.0:
                return
            self._warm_ts = time.monotonic()
        import ray_tpu

        # Fan out, then collect under ONE short total budget: a hung
        # replica must not stall the request path for 5s x N.
        refs = {}
        for name in candidates:
            try:
                refs[name] = self._replica_handle(
                    name
                ).get_warm_shapes.remote()
            except Exception:  # rtlint: disable=swallowed-exception - dead replica: the collect loop below skips it
                pass
        deadline = time.monotonic() + 2.0
        updates: dict[str, set | None] = {}
        for name in candidates:
            ref = refs.get(name)
            if ref is None:
                updates[name] = None
                continue
            try:
                remaining = max(0.05, deadline - time.monotonic())
                updates[name] = set(ray_tpu.get(ref, timeout=remaining))
            except Exception:
                updates[name] = None
        with self._lock:
            for name, warm in updates.items():
                if warm is None:
                    self._warm.pop(name, None)
                else:
                    self._warm[name] = warm

    def choose_replica(self, shape_key: str | None = None) -> str:
        deadline = time.monotonic() + 30.0
        while True:
            self._refresh()
            with self._lock:
                candidates = list(self._replicas)
            if candidates and shape_key:
                self._refresh_warm(candidates)
                warm = [
                    c for c in candidates
                    if shape_key in self._warm.get(c, ())
                ]
                # Prefer warm replicas unless they are saturated — a cold
                # compile beats unbounded queueing behind the warm one.
                warm_free = [
                    c for c in warm
                    if self._ongoing.get(c, 0) < self._max_ongoing
                ]
                if warm_free:
                    candidates = warm_free
            if candidates:
                if len(candidates) == 1:
                    pick = candidates[0]
                else:
                    a, b = random.sample(candidates, 2)
                    pick = a if self._ongoing.get(a, 0) <= self._ongoing.get(b, 0) else b
                if self._ongoing.get(pick, 0) < self._max_ongoing:
                    with self._lock:
                        self._ongoing[pick] = self._ongoing.get(pick, 0) + 1
                    return pick
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no available replica for {self._qualified} "
                    f"(backpressure or scale-to-zero)"
                )
            time.sleep(0.05)
            self._refresh(force=True)

    def on_request_done(self, actor_name: str) -> None:
        with self._lock:
            if actor_name in self._ongoing and self._ongoing[actor_name] > 0:
                self._ongoing[actor_name] -= 1

    def drop_replica(self, actor_name: str) -> None:
        with self._lock:
            self._replicas = [r for r in self._replicas if r != actor_name]
            self._handles.pop(actor_name, None)
            self._banned[actor_name] = time.monotonic() + 10.0


class DeploymentHandle:
    def __init__(self, deployment: str, app_name: str = "default"):
        self.deployment_name = deployment
        self.app_name = app_name
        self._router: Optional[Router] = None
        self._method_name = "__call__"
        self._model_id = ""
        self._shape_key = ""

    def _get_router(self) -> Router:
        if self._router is None:
            self._router = Router(self.deployment_name, self.app_name)
        return self._router

    def options(self, *, method_name: str | None = None,
                multiplexed_model_id: str | None = None,
                shape_key: str | None = None) -> "DeploymentHandle":
        """shape_key: opaque label of the request's compiled shape
        (sequence-length bucket, resolution, ...). Requests with the same
        key stick to replicas that already compiled it (§3.4)."""
        clone = DeploymentHandle(self.deployment_name, self.app_name)
        # Share ONE router across option clones (materialize it now: a
        # None copied here would fork load counts and warm caches later).
        clone._router = self._get_router()
        clone._method_name = method_name or self._method_name
        clone._model_id = multiplexed_model_id or self._model_id
        clone._shape_key = shape_key or self._shape_key
        return clone

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        router = self._get_router()
        meta = RequestMetadata(
            method_name=self._method_name, multiplexed_model_id=self._model_id
        )
        # Compose: upstream DeploymentResponses pass as object refs so the
        # downstream replica reads the value without driver round-trips.
        args = tuple(
            a._to_object_ref() if isinstance(a, DeploymentResponse) else a
            for a in args
        )
        last_exc: Exception | None = None
        for _ in range(3):
            try:
                return self._dispatch_once(router, meta, args, kwargs,
                                           allow_retry=True)
            except Exception as exc:  # replica died between refresh and call
                last_exc = exc
        raise RuntimeError(
            f"could not dispatch to {self.deployment_name}: {last_exc}"
        )

    def _dispatch_once(self, router, meta, args, kwargs,
                       allow_retry: bool) -> DeploymentResponse:
        replica_name = router.choose_replica(
            shape_key=self._shape_key or None
        )
        try:
            replica = router._replica_handle(replica_name)
        except Exception:  # name already unregistered: replica is dead
            router.on_request_done(replica_name)
            router.drop_replica(replica_name)
            raise
        try:
            ref = replica.handle_request.remote(
                {
                    "request_id": meta.request_id,
                    "method_name": meta.method_name,
                    "multiplexed_model_id": meta.multiplexed_model_id,
                    "shape_key": self._shape_key,
                    # Serve-level trace propagation: the proxy's (or any
                    # caller's) current span becomes the replica span's
                    # parent across the actor-call boundary.
                    "trace_ctx": tracing.inject(),
                },
                args,
                kwargs,
            )
        except Exception:
            router.on_request_done(replica_name)
            router.drop_replica(replica_name)
            raise
        # The response can re-dispatch itself ONCE onto another replica if
        # this one dies mid-call (retry=None on the retried response).
        retry = (
            (lambda: self._dispatch_once(router, meta, args, kwargs,
                                         allow_retry=False))
            if allow_retry
            else None
        )
        return DeploymentResponse(
            ref, router, replica_name,
            deployment=self.deployment_name, retry=retry,
        )

    def __reduce__(self):
        return (_rebuild_handle, (self.deployment_name, self.app_name,
                                  self._method_name, self._model_id,
                                  self._shape_key))

    def __repr__(self):
        return f"DeploymentHandle({self.app_name}/{self.deployment_name})"


def _rebuild_handle(deployment, app_name, method_name, model_id,
                    shape_key=""):
    handle = DeploymentHandle(deployment, app_name)
    handle._method_name = method_name
    handle._model_id = model_id
    handle._shape_key = shape_key
    return handle


class _HandlePlaceholder:
    """Marks a bound sub-deployment inside init args; replicas resolve it
    to a live DeploymentHandle at construction time."""

    def __init__(self, deployment: str, app_name: str):
        self.deployment = deployment
        self.app_name = app_name


def _resolve_handle_placeholders(obj: Any) -> Any:
    if isinstance(obj, _HandlePlaceholder):
        return DeploymentHandle(obj.deployment, obj.app_name)
    if isinstance(obj, tuple):
        return tuple(_resolve_handle_placeholders(x) for x in obj)
    if isinstance(obj, list):
        return [_resolve_handle_placeholders(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _resolve_handle_placeholders(v) for k, v in obj.items()}
    return obj

"""Slot-based continuous-batch state (ISSUE 17 tentpole a).

The running decode batch is a fixed array of slots. A sequence occupies
one slot from admission to completion; completed sequences are evicted
per-iteration and their slot re-admitted the very next iteration — the
structural difference from `serve/batching.py`, whose `_BatchQueue`
only forms a new batch at batch boundaries. The active-slot count
rounds up to a configured bucket so the decode step sees a bounded set
of padded shapes (bounded recompilation), exactly like batching.py's
``bucket_sizes`` but re-evaluated every iteration.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional

from ray_tpu.serve._private.common import Deadline


@dataclass
class SequenceState:
    """One in-flight sequence: identity, progress, and its KV pages."""

    request_id: str
    prompt_tokens: List[int]
    max_tokens: int
    session_id: str = ""
    model_id: str = ""
    generated: List[int] = field(default_factory=list)
    # Block ids in the decode replica's KVBlockPool (allocated at
    # admission, freed at eviction).
    kv_blocks: List[int] = field(default_factory=list)
    # Decoded prefill KV payload, held only between arrival and KV-pool
    # allocation (dropped once paged in).
    kv_data: Any = None
    deadline: Deadline = field(default_factory=Deadline.never)
    # Completion surfaces: a future (unary) or an output channel
    # (streaming); the engine completes exactly one of them.
    future: Any = None
    out_chan: Any = None
    admitted_at: float = 0.0

    # -- observability (ISSUE 19) ---------------------------------------
    # Trace context captured at request entry (rides every token event
    # and the terminal timeline record); ``sampled`` is the
    # deterministic seq_trace_sample decision, stable across replays.
    trace_ctx: Any = None
    sampled: bool = False
    # Tokens the client already holds from a pre-death replica (fence
    # dedup drops their replays) — the ledger charges exactly this many
    # to replay_discarded instead of double-counting them productive.
    resume_from: int = 0
    # Monotonic timestamps of the sequence's lifecycle: request entry,
    # slot admission, first token; ``token_times`` collects every
    # emission for inter-token percentiles.
    enqueued_at: float = 0.0
    slot_admitted_at: float = 0.0
    first_token_at: float = 0.0
    token_times: List[float] = field(default_factory=list)
    # Upstream phase durations measured by the decode deployment.
    prefill_s: float = 0.0
    kv_transfer_s: float = 0.0

    def done(self) -> bool:
        return len(self.generated) >= self.max_tokens


class SlotBatch:
    """Fixed-capacity slot table + bucketed padded-shape selection."""

    def __init__(self, max_slots: int, buckets=()):
        self.max_slots = int(max_slots)
        # Keep only buckets the slot table can actually fill, and always
        # close the ladder with max_slots itself (a config whose buckets
        # all exceed max_slots would otherwise leave no valid shape).
        kept = sorted(
            int(b) for b in buckets if 0 < int(b) <= self.max_slots
        )
        if not kept or kept[-1] < self.max_slots:
            kept.append(self.max_slots)
        self.buckets = tuple(kept)
        self.slots: List[Optional[SequenceState]] = [None] * self.max_slots
        self._free: List[int] = list(range(self.max_slots - 1, -1, -1))

    def free_count(self) -> int:
        return len(self._free)

    def occupancy(self) -> int:
        return self.max_slots - len(self._free)

    def admit(self, seq: SequenceState) -> int:
        if not self._free:
            raise RuntimeError("no free slot")
        idx = self._free.pop()
        self.slots[idx] = seq
        return idx

    def evict(self, idx: int) -> Optional[SequenceState]:
        seq = self.slots[idx]
        if seq is not None:
            self.slots[idx] = None
            self._free.append(idx)
        return seq

    def active(self) -> List[tuple]:
        """(slot index, sequence) for every occupied slot, slot order —
        stable iteration order keeps the padded batch layout stable
        between iterations for the same occupancy."""
        return [
            (i, s) for i, s in enumerate(self.slots) if s is not None
        ]

    def bucket_for(self, n: int) -> int:
        """Smallest configured bucket covering ``n`` active slots — the
        padded batch shape this iteration's decode step runs at."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

"""ray_tpu.serve.llm — throughput-first LLM serving on the rtdag plane.

ISSUE 17's serving layer: continuous batching (a resident decode loop
over rtdag channels that admits new sequences into the running batch
every iteration), disaggregated prefill/decode replica pools with KV
blocks crossing pools on the PR-7 block-scaled quantized wire, hash-ring
session affinity across the multi-proxy pool, model multiplexing, and
closed-loop autoscaling off SLO histograms + KV-pool (HBM) headroom.

Public surface::

    from ray_tpu.serve import llm

    app = llm.build_llm_app(llm.LLMConfig(max_slots=64))
    serve.run(app, route_prefix="/llm")
"""

from ray_tpu.serve.llm.batch import SequenceState, SlotBatch
from ray_tpu.serve.llm.config import LLMConfig
from ray_tpu.serve.llm.deployments import (
    LLMDecode,
    LLMPrefill,
    build_llm_app,
)
from ray_tpu.serve.llm.engine import DecodeEngine
from ray_tpu.serve.llm.kv import KVBlockPool
from ray_tpu.serve.llm.wire import (
    KVDeviceWire,
    decode_kv_blocks,
    encode_kv_blocks,
)

__all__ = [
    "LLMConfig",
    "SlotBatch",
    "SequenceState",
    "KVBlockPool",
    "DecodeEngine",
    "KVDeviceWire",
    "encode_kv_blocks",
    "decode_kv_blocks",
    "LLMPrefill",
    "LLMDecode",
    "build_llm_app",
]

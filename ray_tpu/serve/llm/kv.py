"""Paged KV-block pool — one per decode replica (ISSUE 17 tentpole b).

A fixed arena of fixed-size KV blocks (vLLM's PagedAttention layout in
miniature): sequences allocate whole blocks at admission and free them
at eviction, so fragmentation is impossible by construction and "HBM
headroom" is a single number — the free-block fraction — which feeds
both the `rt_serve_kv_blocks_{used,free}` gauges (satellite 2) and the
autoscaler's kv_headroom_min input (tentpole d).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class KVBlockPool:
    """Fixed arena of ``num_blocks`` blocks of ``block_tokens * kv_dim``
    float32 each. Not thread-safe: the decode engine is the only caller
    and runs on one event loop."""

    def __init__(self, num_blocks: int, block_tokens: int, kv_dim: int,
                 *, deployment: str = "", replica_id: str = ""):
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self.kv_dim = int(kv_dim)
        self.block_elems = self.block_tokens * self.kv_dim
        self._arena = np.zeros(
            (self.num_blocks, self.block_elems), dtype=np.float32
        )
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._deployment = deployment
        self._replica_id = replica_id

    # -- accounting -----------------------------------------------------
    def used(self) -> int:
        return self.num_blocks - len(self._free)

    def free(self) -> int:
        return len(self._free)

    def free_frac(self) -> float:
        return len(self._free) / max(1, self.num_blocks)

    def blocks_needed(self, n_tokens: int) -> int:
        return max(1, -(-int(n_tokens) // self.block_tokens))

    # -- alloc/free -----------------------------------------------------
    def alloc(self, n_blocks: int) -> Optional[List[int]]:
        """n block ids, or None when the pool can't cover the request —
        the engine defers the sequence rather than partially allocating."""
        if n_blocks > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n_blocks)]
        return ids

    def release(self, block_ids: List[int]) -> None:
        for bid in block_ids:
            self._arena[bid].fill(0.0)
            self._free.append(bid)

    # -- data -----------------------------------------------------------
    def write(self, block_ids: List[int], kv: np.ndarray) -> None:
        """Page a sequence's prefill KV ((n_tokens, kv_dim) float32) into
        its allocated blocks, zero-padding the tail block."""
        flat = np.asarray(kv, dtype=np.float32).reshape(-1)
        for i, bid in enumerate(block_ids):
            chunk = flat[i * self.block_elems:(i + 1) * self.block_elems]
            self._arena[bid, : chunk.size] = chunk
            if chunk.size < self.block_elems:
                self._arena[bid, chunk.size:] = 0.0

    def read(self, block_ids: List[int]) -> np.ndarray:
        """The sequence's KV pages, stacked (n_blocks, block_elems)."""
        return self._arena[np.asarray(block_ids, dtype=np.intp)]

    # -- observability (satellite 2) ------------------------------------
    def export_gauges(self) -> None:
        from ray_tpu.util.metrics import set_serve_kv_blocks

        set_serve_kv_blocks(
            self._deployment, self._replica_id, self.used(), self.free()
        )

"""Token-level serve-LLM observability (ISSUE 19).

Three cooperating pieces, all owned by the decode replica's event loop:

* **TokenLedger** — the PR-8 goodput discipline applied to tokens:
  every token the decode step issues is eventually classified into
  exactly one of ``productive`` / ``shed`` / ``evicted`` /
  ``replay_discarded`` when its sequence reaches a terminal state, so
  ``issued == classified + in_flight`` holds at every instant and
  ``issued == sum(classes)`` holds once the engine drains. A replayed
  sequence (client resumed after a replica death, ``resume_from`` > 0)
  charges its first ``resume_from`` tokens to ``replay_discarded`` —
  the client's fence dedup drops those on the floor, so counting them
  productive would double-count delivered work.

* **Per-sequence timelines** — one JSONL record per terminal sequence
  (``sequences-<pid>.jsonl`` beside the span files under
  ``<session>/tracing/``): queue/admission wait, prefill time,
  KV-transfer time, TTFT, inter-token p50/p99, the terminal cause, and
  the trace id that followed the sequence through the channel plane.
  The same files carry periodic ``kv`` records (KV-pool headroom over
  time) — the history the diagnose rule fits a least-squares trend to,
  exactly like the node agent's oom_risk projection.

* **Sampling** — ``LLMConfig.seq_trace_sample`` gates the traced path.
  The decision is a deterministic hash of request_id (NOT a PRNG), so
  a replayed sequence keeps its sampling fate — and therefore its
  trace id — across replica deaths. The unsampled/disabled path does
  no span work and writes no timeline records; the ledger and the
  TTFT/TPOT histograms stay on either way (O(1) arithmetic per token,
  gated by the release overhead bench at <=2%).
"""

from __future__ import annotations

import atexit
import glob
import hashlib
import json
import os
import threading
import time

# Terminal ledger classes, in the order summaries render them.
TOKEN_CLASSES = ("productive", "shed", "evicted", "replay_discarded")


def sampled(request_id: str, sample: float) -> bool:
    """Deterministic per-sequence sampling decision: a blake2b hash of
    the request id against the configured fraction. Stable across
    processes and replays (no PRNG state)."""
    if sample >= 1.0:
        return True
    if sample <= 0.0:
        return False
    h = hashlib.blake2b(request_id.encode(), digest_size=4).digest()
    return int.from_bytes(h, "big") / 0xFFFFFFFF < sample


class TokenLedger:
    """Exact-sum token accounting: ``issued`` counts every token the
    decode step emits; terminal classification partitions them."""

    __slots__ = (
        "issued", "productive", "shed", "evicted", "replay_discarded",
        "seqs_shed",
    )

    def __init__(self):
        self.issued = 0
        self.productive = 0
        self.shed = 0
        self.evicted = 0
        self.replay_discarded = 0
        # Sequences shed at admission never issue a token; counted
        # separately so sheds stay visible even though their token
        # contribution is structurally zero.
        self.seqs_shed = 0

    def issue(self, n: int = 1) -> None:
        self.issued += n

    def classify(self, seq, outcome: str) -> dict:
        """Charge a terminal sequence's tokens: the first
        ``resume_from`` of a replayed sequence to ``replay_discarded``
        (the client's fence dedup already has them), the rest to
        ``outcome``. Returns the per-class split for the timeline
        record."""
        n = len(seq.generated)
        replayed = min(max(int(getattr(seq, "resume_from", 0)), 0), n)
        fresh = n - replayed
        self.replay_discarded += replayed
        setattr(self, outcome, getattr(self, outcome) + fresh)
        return {"class": outcome, "tokens": fresh,
                "replay_discarded": replayed}

    def in_flight(self) -> int:
        return self.issued - (
            self.productive + self.shed + self.evicted
            + self.replay_discarded
        )

    def snapshot(self) -> dict:
        return {
            "issued": self.issued,
            "productive": self.productive,
            "shed": self.shed,
            "evicted": self.evicted,
            "replay_discarded": self.replay_discarded,
            "in_flight": self.in_flight(),
            "seqs_shed": self.seqs_shed,
        }


# -- sequence timeline exporter ---------------------------------------------
# Same buffered-JSONL discipline as tracing.py's span exporter (append to
# a thread-safe list, one batched write per flush), shared directory, so
# ``ray_tpu timeline --seq`` and the dashboard read spans and sequence
# records from one place.

_lock = threading.Lock()
_buffer: list[dict] = []
_flusher_started = False
# Age-based drain, same cadence discipline as the span flusher: a
# decode replica writes ONE terminal record per sequence, so waiting
# for a 256-record batch would strand records in memory for minutes.
_FLUSH_AGE_S = 0.5


def _export_path() -> str | None:
    from ray_tpu.util import tracing

    base = tracing._export_dir()
    if base is None:
        return None
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, f"sequences-{os.getpid()}.jsonl")


def _flush_loop() -> None:
    while True:
        time.sleep(_FLUSH_AGE_S)
        try:
            flush()
        except Exception:  # rtlint: disable=swallowed-exception - keep the daemon alive through transient write failures
            pass


def _ensure_flusher() -> None:
    global _flusher_started
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True
    threading.Thread(
        target=_flush_loop, name="raytpu-seq-flusher", daemon=True
    ).start()
    atexit.register(flush)


def record(rec: dict) -> None:
    """Buffer one timeline record (``kind`` in {"seq", "kv"})."""
    with _lock:
        _buffer.append(rec)
        should_flush = len(_buffer) >= 256
    if not _flusher_started:
        _ensure_flusher()
    if should_flush:
        flush()


def flush() -> None:
    with _lock:
        batch, _buffer[:] = _buffer[:], ()
    if not batch:
        return
    path = _export_path()
    if path is None:
        return
    lines = "".join(
        json.dumps(rec, separators=(",", ":")) + "\n" for rec in batch
    )
    with open(path, "a") as fh:
        fh.write(lines)


def read_sequences(session_dir: str) -> list[dict]:
    """Every sequence/kv timeline record exported under a session
    (tests, ``state.summarize_sequences``, the dashboard route)."""
    flush()
    out: list[dict] = []
    for path in sorted(
        glob.glob(os.path.join(session_dir, "tracing",
                               "sequences-*.jsonl"))
    ):
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        out.append(json.loads(line))
        except OSError:
            continue
    return out


def percentile(values, frac: float) -> float:
    """Nearest-rank percentile over a small list (inter-token gaps —
    bounded by max_tokens, so sorting per terminal sequence is cheap)."""
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(frac * len(ordered)))
    return float(ordered[idx])


def seq_record(seq, *, outcome: str, cause: str, split: dict,
               deployment: str, replica_id: str, fence: str) -> dict:
    """Build the terminal timeline record for one sequence. Times are
    relative spans in seconds (monotonic-clock differences), plus one
    wall-clock ``ts`` so cross-process records order coherently."""
    import time

    gaps = [
        b - a for a, b in zip(seq.token_times, seq.token_times[1:])
    ]
    ttft = (
        seq.first_token_at - seq.enqueued_at
        if seq.first_token_at and seq.enqueued_at else 0.0
    )
    queue_wait = (
        seq.slot_admitted_at - seq.enqueued_at
        if seq.slot_admitted_at and seq.enqueued_at else 0.0
    )
    return {
        "kind": "seq",
        "ts": time.time(),
        "request_id": seq.request_id,
        "trace_id": (seq.trace_ctx or {}).get("trace_id", ""),
        "deployment": deployment,
        "replica": replica_id,
        "fence": fence,
        "outcome": outcome,
        "cause": cause,
        "tokens": len(seq.generated),
        "replay_discarded": split.get("replay_discarded", 0),
        "queue_wait_s": round(queue_wait, 6),
        "prefill_s": round(seq.prefill_s, 6),
        "kv_transfer_s": round(seq.kv_transfer_s, 6),
        "ttft_s": round(ttft, 6),
        "tpot_p50_s": round(percentile(gaps, 0.50), 6),
        "tpot_p99_s": round(percentile(gaps, 0.99), 6),
        # Relative token emission times (vs enqueue) for the Perfetto
        # export's instant events; capped so a long generation can't
        # bloat the record.
        "token_rel_s": [
            round(t - seq.enqueued_at, 6) for t in seq.token_times[:512]
        ] if seq.enqueued_at else [],
    }

"""DecodeEngine — the resident continuous-batching decode loop.

The serve-plane analogue of the rtdag executor's ``StageLoop`` (PR 15):
one resident loop per decode replica, riding the rtdag channel family —
admission is a bounded ``LocalChannel``, per-sequence token streams are
``LocalChannel``s, and the prefill KV handoff arrives over the inline or
device wire (wire.py). Every iteration:

1. admit newly-arrived sequences into free slots (continuous batching —
   no batch boundaries; `serve/batching.py` waits for a flush, this
   admits mid-flight),
2. page their prefill KV into the paged block pool,
3. evict deadline-expired sequences,
4. run ONE decode step over the active slots at the covering padded
   bucket shape (bounded recompilation),
5. append/stream tokens and evict completed sequences (their slots are
   free for step 1 of the *next* iteration),
6. export per-iteration slot-occupancy + KV-block gauges (satellite 2).

Steady state is pure in-process work — channel ops, pool arithmetic,
the model step. Zero controller RPCs per iteration, which the release
bench gates exactly like ``compiled_dag_overhead`` does for rtdag.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
import uuid

from ray_tpu import exceptions
from ray_tpu._private import chaos
from ray_tpu.dag.channels import LocalChannel
from ray_tpu.serve.llm import observability as seq_obs
from ray_tpu.serve.llm.batch import SequenceState, SlotBatch
from ray_tpu.serve.llm.config import LLMConfig
from ray_tpu.serve.llm.kv import KVBlockPool
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)


class DecodeEngine:
    """Slot-based continuous batching over rtdag channels. Single-owner:
    all state is touched only from the hosting replica's event loop."""

    # Idle admission wait when the batch is empty (engine parked).
    IDLE_POLL_S = 0.1

    def __init__(self, config: LLMConfig, model, *, deployment: str = "",
                 replica_id: str = ""):
        self.cfg = config
        self.model = model
        self.deployment = deployment
        self.replica_id = replica_id
        self._batch = SlotBatch(config.max_slots, config.slot_buckets)
        self._kv = KVBlockPool(
            config.num_kv_blocks, config.block_tokens, config.kv_dim,
            deployment=deployment, replica_id=replica_id,
        )
        self._admit_chan = LocalChannel(
            maxsize=max(1, config.max_queued_seqs),
            group="serve_llm", label=f"admit-{replica_id}",
        )
        # Sequences whose KV couldn't be paged in yet (pool pressure).
        self._deferred: list[SequenceState] = []
        # Engine fence (PR-16 epoch analogue for token streams): every
        # emitted token carries (fence, index). A client resuming a
        # stream after a replica death sees a NEW fence from the retry
        # replica and dedups by index — tokens are delivered exactly
        # once even when decode replays from scratch.
        self.fence = uuid.uuid4().hex[:8]
        self._task: asyncio.Task | None = None
        self._stopped = False
        # Stats.
        self.iterations = 0
        self.admitted = 0
        self.completed = 0
        self.shed = 0
        self.expired = 0
        self._last_bucket = 0
        self._occupancy_ewma = 0.0
        self._iter_rate = 0.0  # iterations/s EWMA
        self._last_iter_t = 0.0
        # Token goodput ledger (ISSUE 19): always on — O(1) integer
        # arithmetic per token, classification once per terminal seq.
        self.ledger = seq_obs.TokenLedger()
        self._last_kv_note_t = 0.0

    # -- lifecycle ------------------------------------------------------
    def ensure_started(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()

    # -- admission ------------------------------------------------------
    def retry_after_estimate(self) -> float:
        """Seconds until a slot plausibly frees: the closest-to-done
        active sequence's remaining tokens at the observed iteration
        rate. This seeds the shed response's Retry-After hint (capped by
        the caller's remaining deadline budget at the proxy)."""
        active = self._batch.active()
        if not active or self._iter_rate <= 0:
            return 0.05
        remaining = min(
            s.max_tokens - len(s.generated) for _, s in active
        )
        return max(0.01, remaining / self._iter_rate)

    async def submit(self, seq: SequenceState) -> SequenceState:
        """Admit a sequence into the engine, shedding fast when the
        running batch AND the admission queue are full — the router's
        retry/backoff (or the proxy's 503 + Retry-After) handles it."""
        self.ensure_started()
        backlog = (
            self._admit_chan.qsize() + len(self._deferred)
        )
        if (
            self._batch.free_count() == 0
            and backlog >= self.cfg.max_queued_seqs
        ):
            self.shed += 1
            self.ledger.seqs_shed += 1
            self._seq_record(seq, outcome="shed", cause="admission_shed",
                             split={})
            est = self.retry_after_estimate()
            raise exceptions.RequestShedError(
                f"decode batch full ({self._batch.occupancy()} slots, "
                f"{backlog} queued); retry_after_s={est:.3f}",
                retry_after_s=est,
            )
        if seq.out_chan is None:
            seq.future = asyncio.get_running_loop().create_future()
        seq.admitted_at = time.monotonic()
        if not seq.enqueued_at:
            # Raw engine submissions (tests, custom deployments) that
            # skipped the deployment's entry stamp still get a queue
            # baseline.
            seq.enqueued_at = seq.admitted_at
        await self._admit_chan.put(seq)
        return seq

    # -- the resident loop ----------------------------------------------
    async def _loop(self) -> None:
        logger.info(
            "decode engine %s: resident loop up (slots=%d buckets=%s "
            "kv_blocks=%d fence=%s)", self.replica_id, self.cfg.max_slots,
            list(self._batch.buckets), self.cfg.num_kv_blocks, self.fence,
        )
        try:
            while not self._stopped:
                await self._iterate()
        except asyncio.CancelledError:
            pass
        except Exception as exc:
            # A decode-loop crash must not strand submitters on futures
            # that will never resolve: fail every in-flight sequence
            # loudly, then let the next submit() restart the loop.
            logger.exception(
                "decode engine %s: loop crashed", self.replica_id
            )
            for idx, seq in self._batch.active():
                self._batch.evict(idx)
                self._release(seq)
                self._finish_ledger(seq, "shed", "engine_crash")
                await self._finish_error(seq, exc)
            for seq in self._deferred:
                self._finish_ledger(seq, "shed", "engine_crash")
                await self._finish_error(seq, exc)
            self._deferred = []

    async def _iterate(self) -> None:
        # 1. page in deferred sequences first (they arrived earlier and
        # eviction may have freed the pool since last iteration).
        if self._deferred:
            still: list[SequenceState] = []
            for seq in self._deferred:
                if not self._try_page_in(seq):
                    still.append(seq)
            self._deferred = still
        # 2. admit arrivals into free slots. When the batch is live, wait
        # at most admit_poll_s (admission latency is one iteration); when
        # the engine is idle, park on the channel instead of spinning.
        free = self._batch.free_count()
        if free > 0:
            busy = self._batch.occupancy() > 0 or self._deferred
            arrivals = await self._admit_chan.pop_batch(
                free, self.cfg.admit_poll_s if busy else self.IDLE_POLL_S
            )
            for seq in arrivals:
                if not self._try_page_in(seq):
                    self._deferred.append(seq)
        # 3. deadline eviction — queued or running, an expired sequence
        # wastes a slot on an answer nobody is waiting for.
        for idx, seq in self._batch.active():
            if seq.deadline.expired():
                self._batch.evict(idx)
                self._release(seq)
                self.expired += 1
                self._finish_ledger(seq, "evicted", "deadline")
                await self._finish_error(
                    seq, exceptions.DeadlineExceededError(
                        "sequence deadline expired mid-decode"
                    ),
                )
        self._deferred = [
            s for s in self._deferred
            if not (s.deadline.expired() and self._expire_deferred(s))
        ]
        active = self._batch.active()
        if not active:
            return
        # Chaos hook (ISSUE 13 schedule): an armed mid-decode kill takes
        # the replica down between iterations — the handle's death retry
        # re-prefills on a sibling and the stream fence dedups tokens.
        try:
            chaos.failpoint("serve.llm.decode_iter")
        except chaos.ChaosFault:
            os._exit(1)
        # 4. one decode step over the active slots at the covering
        # padded bucket (bounded recompilation), KV pages gathered from
        # the paged pool.
        bucket = self._batch.bucket_for(len(active))
        if bucket != self._last_bucket:
            from ray_tpu.serve import batching

            batching.note_warm_shape(f"llm:{bucket}")
            self._last_bucket = bucket
        seqs = [s for _, s in active]
        kv_pages = [self._kv.read(s.kv_blocks) for s in seqs]
        # decode.iter span (ISSUE 19): parented on the first sampled
        # active sequence's trace, so the iteration that produced a
        # token shows up inside that sequence's trace tree. Unsampled
        # iterations pay one generator-free any() scan.
        iter_span = None
        if tracing.enabled():
            parent = next(
                (s.trace_ctx for s in seqs if s.sampled and s.trace_ctx),
                None,
            )
            if parent is not None:
                iter_span = tracing.begin(
                    "decode.iter", parent=parent, replica=self.replica_id,
                    slots=len(active), bucket=bucket,
                )
        tokens = self.model.decode_step(seqs, kv_pages, bucket)
        # 5. append/stream tokens; evict completed sequences.
        self.ledger.issue(len(active))
        now_t = time.monotonic()
        for (idx, seq), tok in zip(active, tokens):
            seq.generated.append(int(tok))
            prev_t = seq.token_times[-1] if seq.token_times else 0.0
            seq.token_times.append(now_t)
            if len(seq.generated) == 1:
                seq.first_token_at = now_t
                self._observe_token("ttft", now_t - seq.enqueued_at)
            elif prev_t:
                self._observe_token("tpot", now_t - prev_t)
            if seq.out_chan is not None:
                event = {
                    "i": len(seq.generated) - 1, "t": int(tok),
                    "fence": self.fence,
                }
                if seq.sampled and seq.trace_ctx:
                    # The trace id follows every token to the client:
                    # visible in the event AND riding the LocalChannel
                    # envelope for the stream reader's last_trace.
                    event["tr"] = seq.trace_ctx["trace_id"]
                await seq.out_chan.put(
                    event,
                    trace=seq.trace_ctx if seq.sampled else None,
                )
            if seq.done():
                self._batch.evict(idx)
                self._release(seq)
                self.completed += 1
                self._finish_ledger(seq, "productive", "completed")
                await self._finish_ok(seq)
        if iter_span is not None:
            tracing.finish(iter_span)
        # 6. per-iteration bookkeeping + gauges (satellite 2).
        self.iterations += 1
        now = time.monotonic()
        if self._last_iter_t:
            dt = max(1e-6, now - self._last_iter_t)
            self._iter_rate = 0.9 * self._iter_rate + 0.1 / dt
        self._last_iter_t = now
        occ = len(active)
        self._occupancy_ewma = 0.9 * self._occupancy_ewma + 0.1 * occ
        self._export_gauges(occ, bucket)
        await asyncio.sleep(0)

    # -- sequence completion --------------------------------------------
    def _try_page_in(self, seq: SequenceState) -> bool:
        if self._batch.free_count() == 0:
            return False
        n = self._kv.blocks_needed(len(seq.prompt_tokens))
        ids = self._kv.alloc(n)
        if ids is None:
            return False
        if seq.kv_data is not None:
            self._kv.write(ids, seq.kv_data)
            seq.kv_data = None
        seq.kv_blocks = ids
        seq.slot_admitted_at = time.monotonic()
        self._batch.admit(seq)
        self.admitted += 1
        if seq.model_id:
            from ray_tpu.serve import multiplex

            multiplex.pin_model(seq.model_id)
        return True

    def _release(self, seq: SequenceState) -> None:
        if seq.kv_blocks:
            self._kv.release(seq.kv_blocks)
            seq.kv_blocks = []
        if seq.model_id:
            from ray_tpu.serve import multiplex

            multiplex.unpin_model(seq.model_id)

    def _expire_deferred(self, seq: SequenceState) -> bool:
        self.expired += 1
        self._finish_ledger(seq, "evicted", "kv_wait_deadline")
        task = asyncio.get_running_loop().create_task(
            self._finish_error(seq, exceptions.DeadlineExceededError(
                "sequence deadline expired before a KV page freed"
            ))
        )
        # Keep a strong ref until it runs (create_task result unused
        # otherwise gets GC'd mid-flight).
        task.add_done_callback(lambda _t: None)
        return True

    async def _finish_ok(self, seq: SequenceState) -> None:
        if seq.out_chan is not None:
            await seq.out_chan.put({
                "done": True, "n": len(seq.generated), "fence": self.fence,
            })
        elif seq.future is not None and not seq.future.done():
            seq.future.set_result({
                "request_id": seq.request_id,
                "tokens": list(seq.generated),
                "fence": self.fence,
            })

    async def _finish_error(self, seq: SequenceState, exc: Exception) -> None:
        if seq.out_chan is not None:
            await seq.out_chan.put({
                "error": f"{type(exc).__name__}: {exc}",
                "fence": self.fence,
            })
        elif seq.future is not None and not seq.future.done():
            seq.future.set_exception(exc)

    # -- observability --------------------------------------------------
    def _finish_ledger(self, seq: SequenceState, outcome: str,
                       cause: str) -> None:
        """Terminal accounting for one sequence: partition its tokens
        in the ledger, mirror the split into the Prometheus token
        counters, and (for sampled sequences) write the per-sequence
        timeline record."""
        split = self.ledger.classify(seq, outcome)
        try:
            from ray_tpu.util import metrics as metrics_mod

            metrics_mod.inc_serve_tokens(
                outcome, split["tokens"], self.deployment
            )
            metrics_mod.inc_serve_tokens(
                "replay_discarded", split["replay_discarded"],
                self.deployment,
            )
        except Exception:  # rtlint: disable=swallowed-exception - metric export must never stall the decode loop
            pass
        self._seq_record(seq, outcome=outcome, cause=cause, split=split)

    def _seq_record(self, seq: SequenceState, *, outcome: str, cause: str,
                    split: dict) -> None:
        if not seq.sampled:
            return
        try:
            seq_obs.record(seq_obs.seq_record(
                seq, outcome=outcome, cause=cause, split=split,
                deployment=self.deployment, replica_id=self.replica_id,
                fence=self.fence,
            ))
        except Exception:  # rtlint: disable=swallowed-exception - timeline export must never stall the decode loop
            pass

    def _observe_token(self, kind: str, seconds: float) -> None:
        try:
            from ray_tpu.util import metrics as metrics_mod

            metrics_mod.record_serve_token_latency(
                kind, seconds, self.deployment
            )
        except Exception:  # rtlint: disable=swallowed-exception - metric export must never stall the decode loop
            pass

    def _export_gauges(self, occupancy: int, bucket: int) -> None:
        try:
            from ray_tpu.util import metrics as metrics_mod

            metrics_mod.set_serve_replica_gauge(
                "slot_occupancy", self.deployment, self.replica_id,
                occupancy,
            )
            metrics_mod.inc_serve_tokens(
                "issued", occupancy, self.deployment
            )
            self._kv.export_gauges()
        except Exception:  # rtlint: disable=swallowed-exception - metric export must never stall the decode loop
            pass
        now = time.monotonic()
        if now - self._last_kv_note_t >= 0.5:
            # KV-headroom history rides the sequence timeline files —
            # the series the diagnose rule fits its exhaustion trend to
            # (the PR-5 oom_risk shape, least-squares over (ts, free)).
            self._last_kv_note_t = now
            try:
                seq_obs.record({
                    "kind": "kv", "ts": time.time(),
                    "deployment": self.deployment,
                    "replica": self.replica_id,
                    "kv_free_frac": round(self._kv.free_frac(), 4),
                    "kv_blocks_used": self._kv.used(),
                    "kv_blocks_free": self._kv.free(),
                })
            except Exception:  # rtlint: disable=swallowed-exception - timeline export must never stall the decode loop
                pass

    def queue_depth(self) -> int:
        return self._admit_chan.qsize() + len(self._deferred)

    def stats(self) -> dict:
        """Per-iteration view for replica.get_metrics(): slot occupancy
        replaces the batch-boundary occupancy the PR-8 gauge read."""
        occ = self._batch.occupancy()
        bucket = self._batch.bucket_for(occ) if occ else 0
        return {
            "slot_occupancy": occ,
            "slot_occupancy_frac": (occ / bucket) if bucket else 0.0,
            "avg_slot_occupancy": round(self._occupancy_ewma, 3),
            "decode_bucket": bucket,
            "iterations": self.iterations,
            "iter_rate_s": round(self._iter_rate, 3),
            "admitted": self.admitted,
            "completed": self.completed,
            "shed": self.shed,
            "expired": self.expired,
            "queue_depth": self.queue_depth(),
            "kv_blocks_used": self._kv.used(),
            "kv_blocks_free": self._kv.free(),
            "kv_free_frac": round(self._kv.free_frac(), 4),
            "fence": self.fence,
            "token_ledger": self.ledger.snapshot(),
        }

    def load(self) -> dict:
        """Autoscaler inputs (tentpole d): ongoing slots + queued
        sequences, and the KV-pool free fraction — the decode twin's
        HBM-headroom signal (PR-5's oom-risk analogue)."""
        return {
            "ongoing": self._batch.occupancy(),
            "queue_depth": self.queue_depth(),
            "kv_free_frac": self._kv.free_frac(),
        }

"""Prefill/decode deployments + app builder (tentpole b, c, d).

Disaggregation layout (the MindSpeed-RL dataflow split applied to
serving): ``LLMPrefill`` replicas run the compute-bound prompt pass and
emit KV blocks on the quantized wire; ``LLMDecode`` replicas own a
paged KV pool and the resident continuous-batching engine. The two are
separate serve deployments, so the controller autoscales the pools
independently — prefill off queue depth/SLO (prompt-bound load),
decode off slot occupancy and KV headroom (memory-bound load).

A generate request enters through the decode pool (hash-ring session
affinity keeps a session on the replica caching its state), which calls
the prefill pool through a DeploymentHandle: the KV payload rides the
reply (the inline wire). The ``wire.KVDeviceWire`` transport moves the
same payload worker→worker over the collective p2p plane when a group
is available.

The default model is a deterministic toy LM: token *i* of a sequence is
a digest of (model id, prompt, i), so retried/replayed decodes reproduce
byte-identical tokens — which is what makes the chaos tests' exactly-
once assertions sharp. Real models subclass and override the two hooks.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from typing import Any, List, Optional

import numpy as np

from ray_tpu._private import chaos
from ray_tpu.serve._private.common import Deadline, current_deadline
from ray_tpu.serve.llm import observability as seq_obs
from ray_tpu.serve.llm.batch import SequenceState
from ray_tpu.serve.llm.config import LLMConfig
from ray_tpu.serve.llm.engine import DecodeEngine
from ray_tpu.serve.llm.wire import decode_kv_blocks, encode_kv_blocks
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)


def _digest(*parts) -> int:
    h = hashlib.blake2b(
        "|".join(str(p) for p in parts).encode(), digest_size=8
    )
    return int.from_bytes(h.digest(), "big")


def tokenize(prompt) -> List[int]:
    """Prompts are strings (whitespace-hashed) or token-id lists."""
    if isinstance(prompt, str):
        return [_digest("tok", w) % 50000 for w in prompt.split() or [""]]
    return [int(t) for t in prompt]


class ToyLM:
    """Deterministic stand-in model: prefill emits smooth KV in [-1, 1]
    (friendly to the block-scaled int8 wire), decode emits digest tokens
    reproducible across replicas and restarts."""

    def __init__(self, config: LLMConfig):
        self.cfg = config

    def prefill(self, tokens: List[int], model_id: str = "") -> np.ndarray:
        t = np.asarray(tokens, dtype=np.float64)
        pos = np.arange(1, self.cfg.kv_dim + 1, dtype=np.float64)
        seed = (_digest("m", model_id) % 997) / 997.0
        kv = np.sin(np.outer(t * 1e-3 + seed, pos * 0.1))
        if self.cfg.prefill_flops > 0:
            # Synthetic compute knob: emulate a prompt pass.
            n = max(2, int(self.cfg.prefill_flops ** 0.5))
            a = np.ones((n, n), dtype=np.float32)
            a @ a
        return kv.astype(np.float32)

    def decode_step(self, seqs, kv_pages, bucket: int) -> List[int]:
        """One token for every active slot. The batch is padded to the
        bucket shape so the 'compiled' step sees a bounded shape set —
        the padding rows are dead weight exactly like batching.py's."""
        pad = bucket - len(seqs)
        if self.cfg.decode_flops > 0:
            n = max(2, int(self.cfg.decode_flops ** 0.5))
            a = np.ones((bucket, n), dtype=np.float32)
            a @ np.ones((n, n), dtype=np.float32)
        del pad, kv_pages  # toy decode: KV fidelity is tracked wire-side
        return [
            _digest(s.model_id, tuple(s.prompt_tokens), len(s.generated))
            % self.cfg.vocab_size
            for s in seqs
        ]


class _ModelAdapter:
    """A multiplexed 'model' (LoRA-analogue): the weights are the id;
    the object exists to exercise the load/checkpoint/unload lifecycle
    and the pin-before-evict drain fix (satellite 6)."""

    def __init__(self, model_id: str):
        self.model_id = model_id
        self.loaded_at = time.monotonic()
        self.checkpointed = 0

    def checkpoint(self) -> None:
        self.checkpointed += 1

    def unload(self) -> None:
        pass


class LLMPrefill:
    """Prefill pool replica: tokenize, prompt pass, encode KV for the
    wire. Stateless per request — prefill autoscales on pure throughput."""

    def __init__(self, config: Any = None):
        self.cfg = LLMConfig.from_any(config)
        self._wire_cfg = self.cfg.wire_config()
        self._model = ToyLM(self.cfg)
        self._served = 0

    async def prefill(self, body: dict) -> dict:
        extra = chaos.latency_delay("serve.llm.prefill")
        if extra > 0:
            await asyncio.sleep(extra)
        prompts = body.get("prompts") or [body.get("prompt", "")]
        model_id = str(body.get("model", "") or "")
        seqs = []
        for prompt in prompts:
            tokens = tokenize(prompt)
            kv = self._model.prefill(tokens, model_id)
            payload = encode_kv_blocks(kv, self._wire_cfg)
            seqs.append({
                "tokens": tokens,
                "kv": payload,
                # Wire-fidelity checksum: decode compares the payload
                # roundtrip against this to track quantization error.
                "sig": float(np.mean(np.abs(kv))),
            })
        self._served += len(seqs)
        return {
            "seqs": seqs,
            "quantized": bool(self._wire_cfg),
            "served": self._served,
        }

    async def __call__(self, body: dict) -> dict:
        return await self.prefill(body if isinstance(body, dict) else {})


class LLMDecode:
    """Decode pool replica: hosts the resident continuous-batching
    engine and the paged KV pool; calls the prefill pool for prompt
    passes (model composition — the KV payload rides the reply)."""

    def __init__(self, config: Any = None, prefill: Any = None):
        self.cfg = LLMConfig.from_any(config)
        self._engine = DecodeEngine(
            self.cfg, ToyLM(self.cfg), deployment="llm_decode",
        )
        self._prefill = prefill  # DeploymentHandle or None (single-pool)
        self._local_prefill = LLMPrefill(self.cfg)
        self._kv_wire_err = 0.0

    # -- multiplexing (tentpole c) --------------------------------------
    # Definition-time decorator (serve.multiplexed binds its LRU at
    # import): the per-replica cap rides the class attribute below.
    from ray_tpu.serve.multiplex import multiplexed as _multiplexed

    @_multiplexed(max_num_models_per_replica=3)
    async def _load_model(self, model_id: str) -> _ModelAdapter:
        return _ModelAdapter(model_id)

    del _multiplexed

    # -- prefill hop ----------------------------------------------------
    def _run_prefill(self, payload: dict) -> dict:
        handle = self._prefill.options(method_name="prefill")
        return handle.remote(payload).result()

    async def _prefill_seqs(self, prompts: list, model_id: str) -> list:
        payload = {"prompts": prompts, "model": model_id}
        # The serve.prefill span makes the prompt-pass hop a visible
        # phase of the request's trace (the ambient serve.replica span
        # parents it; the handle call's own submit/execute spans chain
        # underneath). span() is a no-op yield when tracing is off.
        with tracing.span(
            "serve.prefill", prompts=len(prompts),
            inline=self._prefill is None,
        ):
            if self._prefill is None:
                out = await self._local_prefill.prefill(payload)
            else:
                # One RPC per admission batch, not per sequence;
                # to_thread keeps the blocking handle call off the
                # decode loop, and copies the ambient deadline
                # contextvar with it.
                out = await asyncio.to_thread(self._run_prefill, payload)
        return out["seqs"]

    def _make_seq(self, entry: dict, body: dict, model_id: str,
                  deadline: Deadline, *, enqueued_at: float = 0.0,
                  prefill_s: float = 0.0) -> SequenceState:
        import uuid

        t0 = time.monotonic()
        kv = decode_kv_blocks(entry["kv"])
        kv_transfer_s = time.monotonic() - t0
        err = abs(float(np.mean(np.abs(kv))) - entry.get("sig", 0.0))
        self._kv_wire_err = 0.9 * self._kv_wire_err + 0.1 * err
        request_id = str(
            body.get("request_id", "") or uuid.uuid4().hex[:12]
        )
        seq = SequenceState(
            request_id=request_id,
            prompt_tokens=entry["tokens"],
            max_tokens=int(
                body.get("max_tokens", self.cfg.max_tokens_default)
            ),
            session_id=str(body.get("session_id", "") or ""),
            model_id=model_id,
            kv_data=kv,
            deadline=deadline,
        )
        seq.enqueued_at = enqueued_at
        seq.prefill_s = prefill_s
        seq.kv_transfer_s = kv_transfer_s
        # Client hint after a replica-death retry: how many tokens it
        # already delivered under the previous fence. The ledger
        # charges exactly that many replays to replay_discarded.
        seq.resume_from = int(body.get("resume_from", 0) or 0)
        # Deterministic sampling keeps a replayed request's tracing
        # fate (and trace id, carried in the retried request's ambient
        # context) stable across replicas.
        seq.sampled = tracing.enabled() and seq_obs.sampled(
            request_id, self.cfg.seq_trace_sample
        )
        if seq.sampled:
            seq.trace_ctx = tracing.inject()
            if seq.trace_ctx and kv_transfer_s > 0:
                # Backdated span for the KV decode hop (inline wire):
                # the sampling decision needs request_id, which is only
                # known after the decode ran.
                end_ns = time.time_ns()
                tracing.emit(
                    "serve.kv_transfer", seq.trace_ctx,
                    start_ns=end_ns - int(kv_transfer_s * 1e9),
                    end_ns=end_ns, request_id=request_id,
                    quantized=entry["kv"][0] != "__kv_exact",
                )
        return seq

    # -- request surface ------------------------------------------------
    async def generate(self, body: Any = None):
        """One sequence. ``stream=True`` returns an async generator of
        ``{"i", "t", "fence"}`` token events (the replica wraps it in an
        rtdag LocalChannel stream); otherwise awaits completion."""
        body = body if isinstance(body, dict) else {"prompt": body or ""}
        t0 = time.monotonic()
        deadline = current_deadline() or Deadline.never()
        model_id = str(body.get("model", "") or "")
        if model_id:
            await self._load_model(model_id)
        entries = await self._prefill_seqs(
            [body.get("prompt", "")], model_id
        )
        prefill_s = time.monotonic() - t0
        seq = self._make_seq(
            entries[0], body, model_id, deadline,
            enqueued_at=t0, prefill_s=prefill_s,
        )
        if body.get("stream"):
            from ray_tpu.dag.channels import LocalChannel

            seq.out_chan = LocalChannel(
                maxsize=seq.max_tokens + 8, group="serve_llm",
                label=f"out-{seq.request_id}",
            )
            await self._engine.submit(seq)

            async def _token_events():
                while True:
                    events = await seq.out_chan.pop_batch(
                        64, max(0.05, deadline.remaining(cap=30.0))
                    )
                    if not events and deadline.expired():
                        raise TimeoutError("stream deadline expired")
                    for event in events:
                        if event.get("done"):
                            return
                        if "error" in event:
                            raise RuntimeError(event["error"])
                        yield event

            return _token_events()
        await self._engine.submit(seq)
        return await seq.future

    async def generate_batch(self, body: dict) -> dict:
        """Admission-batched unary path (the bench driver): one prefill
        RPC and one admission wave for N sequences, completion gathered
        per-sequence as slots finish."""
        body = body if isinstance(body, dict) else {}
        t0 = time.monotonic()
        deadline = current_deadline() or Deadline.never()
        model_id = str(body.get("model", "") or "")
        if model_id:
            await self._load_model(model_id)
        prompts = list(body.get("prompts", ()))
        entries = await self._prefill_seqs(prompts, model_id)
        prefill_s = time.monotonic() - t0
        seqs = [
            self._make_seq(
                e, body, model_id, deadline,
                enqueued_at=t0, prefill_s=prefill_s,
            )
            for e in entries
        ]
        for seq in seqs:
            await self._engine.submit(seq)
        results = await asyncio.gather(*(s.future for s in seqs))
        return {"results": list(results), "fence": self._engine.fence}

    async def __call__(self, body: Any = None):
        return await self.generate(body)

    # -- control/observability ------------------------------------------
    def serve_llm_stats(self) -> dict:
        stats = self._engine.stats()
        stats["kv_wire_err"] = round(self._kv_wire_err, 6)
        return stats

    def serve_llm_load(self) -> dict:
        return self._engine.load()

    async def steady_rpc_probe(self, iters: int = 100,
                               timeout_s: float = 30.0,
                               windows: int = 3) -> dict:
        """The compiled_dag_overhead gate, serve-side: run ``iters``
        decode iterations under whatever traffic is flowing and count
        controller RPCs issued by this process meanwhile. Steady-state
        continuous batching must report 0. Two controller calls are
        BACKGROUND UPLINKS, not decode-loop work, and are subtracted
        by method name: the batched metrics flush (one kv_multi_put
        per 2s tick) and the throttled task-event report (one
        report_task_events per ~1s, batch-size-capped) — both fire at
        their own constant cadence whether or not the engine iterates,
        so under load a 100-iteration window outlasting their period
        would alias them into every window. Anything else that shows
        up is a real finding; the per-method split is returned so a
        nonzero count names its source."""
        from ray_tpu._private.worker import get_global_context

        if isinstance(iters, dict):  # HTTP-style dict body, like generate()
            body, iters = iters, 100
            iters = int(body.get("iters", iters))
            timeout_s = float(body.get("timeout_s", timeout_s))
            windows = int(body.get("windows", windows))

        uplinks = ("kv_multi_put", "report_task_events")
        ctrl = get_global_context().controller
        best: int | None = None
        best_methods: dict[str, int] = {}
        total_iters = 0
        deadline = time.monotonic() + timeout_s
        for _ in range(max(1, windows)):
            start_iter = self._engine.iterations
            calls0 = ctrl.calls_total
            methods0 = dict(ctrl.calls_by_method)
            while (
                self._engine.iterations < start_iter + iters
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.005)
            deltas = {
                m: n - methods0.get(m, 0)
                for m, n in ctrl.calls_by_method.items()
                if n - methods0.get(m, 0) > 0
            }
            window_rpcs = (
                ctrl.calls_total - calls0
                - sum(deltas.get(m, 0) for m in uplinks)
            )
            total_iters += self._engine.iterations - start_iter
            if best is None or window_rpcs < best:
                best = window_rpcs
                best_methods = {
                    m: n for m, n in deltas.items() if m not in uplinks
                }
        return {
            "iterations": total_iters,
            "controller_rpcs": best,
            "rpc_methods": best_methods,
        }


def build_llm_app(
    config: Any = None,
    *,
    prefill_replicas: int = 1,
    decode_replicas: int = 1,
    prefill_autoscaling: Optional[dict] = None,
    decode_autoscaling: Optional[dict] = None,
    max_ongoing_requests: int = 256,
    request_timeout_s: float = 60.0,
    prefill_options: Optional[dict] = None,
    decode_options: Optional[dict] = None,
):
    """Bind the disaggregated app: decode pool (ingress) composed over
    the prefill pool. Pass autoscaling dicts to let each pool resize
    independently (tentpole d) — decode's config may set
    ``kv_headroom_min`` to scale on KV-pool pressure before SLO breach.
    ``prefill_options``/``decode_options`` are extra serve.deployment
    kwargs per pool (retry_policy, health_check_period_s, ...)."""
    from ray_tpu import serve

    cfg = LLMConfig.from_any(config).to_dict()
    prefill_dep = serve.deployment(
        LLMPrefill,
        name="llm_prefill",
        num_replicas=prefill_replicas,
        max_ongoing_requests=max_ongoing_requests,
        autoscaling_config=prefill_autoscaling,
        request_timeout_s=request_timeout_s,
        **(prefill_options or {}),
    )
    decode_dep = serve.deployment(
        LLMDecode,
        name="llm_decode",
        num_replicas=decode_replicas,
        max_ongoing_requests=max_ongoing_requests,
        autoscaling_config=decode_autoscaling,
        request_timeout_s=request_timeout_s,
        **(decode_options or {}),
    )
    return decode_dep.bind(cfg, prefill_dep.bind(cfg))

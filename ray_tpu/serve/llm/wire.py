"""KV-block wire: prefill → decode handoff payloads (tentpole b).

Two transports share one codec:

* **inline** — the KV payload rides the prefill RPC reply (the decode
  replica calls the prefill pool through a DeploymentHandle and the
  encoded blocks come back in the result). Always available; this is
  what the release bench runs.
* **device** — ``KVDeviceWire``: the payload moves worker→worker over
  the collective p2p ring (the PR-15 device-channel plane), tagged
  ``kvblk:p{epoch}:e{src}:{dst}:{seq}`` with all-integer holes so the
  static commgraph extractor folds every KV wire to one certified
  skeleton, and the epoch hole fences pre-crash frames out of re-opened
  wires exactly like rtdag's ``dagch:`` tags (PR-16): a frame sent
  before a recovery epoch bump lands in a mailbox no post-recovery pop
  ever reads.

Payloads are block-scale quantized with the PR-7 codec when the config
carries a wire quantize mode; ``kv_wire_quantize=None`` is the exact-
wire fallback knob. Error feedback stays off — a KV handoff is one-shot,
residuals would never be consumed again.
"""

from __future__ import annotations

import time

import numpy as np

from ray_tpu.util import tracing
from ray_tpu.util.collective import flight

# Trace-carrying envelope marker, shared shape with the rtdag device
# wire's (ISSUE 19): a sampled trace context rides
# ``(marker, ctx, payload)`` — the untraced payload is byte-identical
# to the PR-17 wire.
_TR_WIRE = "__tr"

# Self-describing payload markers (same idiom as the pipeline activation
# wire's "__act" envelope, so mixed exact/quantized wires share one
# decode path).
_KV_EXACT = "__kv_exact"
_KV_Q = "__kv_q"


def encode_kv_blocks(kv: np.ndarray, wire_cfg=None) -> tuple:
    """(marker, shape, payload): exact float32 bytes, or the PR-7
    block-scaled encoding when ``wire_cfg`` requests quantization."""
    kv = np.ascontiguousarray(kv, dtype=np.float32)
    if wire_cfg is None or not getattr(wire_cfg, "quantize", None):
        return (_KV_EXACT, kv.shape, kv)
    from ray_tpu.util.collective.quantization import encode

    return (_KV_Q, kv.shape, encode(kv.reshape(-1), wire_cfg))


def decode_kv_blocks(payload: tuple) -> np.ndarray:
    marker, shape, data = payload
    if marker == _KV_EXACT:
        return np.asarray(data, dtype=np.float32).reshape(shape)
    if marker == _KV_Q:
        from ray_tpu.util.collective.quantization import decode

        return decode(data).reshape(shape).astype(np.float32)
    raise ValueError(f"unknown KV wire marker: {marker!r}")


def wire_error(original: np.ndarray, payload: tuple) -> float:
    """Mean |roundtrip - original| — the KV wire fidelity stat the decode
    engine reports (quantized wires must stay near-exact; the exact wire
    must be exactly zero)."""
    back = decode_kv_blocks(payload)
    return float(np.mean(np.abs(back - np.asarray(original, np.float32))))


class KVDeviceWire:
    """One prefill→decode edge on the collective p2p plane.

    ``src``/``dst`` are the wire's rank endpoints inside the group,
    ``epoch`` is the channel epoch (bumped by the supervisor on replica
    recovery — see ``bump_epoch``), and ``seq`` is the per-wire handoff
    ordinal. The tag skeleton has all-integer holes, so commgraph folds
    every call site to ``kvblk:p{}:e{}:{}:{}`` and certifies the push
    against the pop like any rtdag device edge.
    """

    def __init__(self, group, peer: int, *, src: int = 0, dst: int = 1,
                 epoch: int = 0, wire_cfg=None):
        self._group = group
        self._peer = peer
        self._src = src
        self._dst = dst
        self._wire_cfg = wire_cfg
        self.epoch = epoch
        # Trace context of the most recent pop (single-consumer wire).
        self.last_trace: dict | None = None

    def bump_epoch(self) -> None:
        """Fence the wire after a peer recovery: frames tagged with the
        old epoch become unreadable by construction, so a replayed
        handoff is delivered exactly once (PR-16 semantics)."""
        self.epoch += 1

    def push(self, seq: int, kv: np.ndarray,
             trace: dict | None = None) -> None:
        tag = f"kvblk:p{self.epoch}:e{self._src}:{self._dst}:{seq}"
        payload = encode_kv_blocks(kv, self._wire_cfg)
        ctx = trace if trace is not None else tracing.inject()
        span = None
        if ctx is not None:
            span = tracing.begin(
                "channel.push", parent=ctx, channel=tag,
                family="kv_wire", seq=seq, nbytes=int(kv.nbytes),
            )
            # The producer-side span's OWN context rides the wire so
            # the consumer's channel.pop parents on it (same causal
            # chain as the rtdag device channel).
            payload = (
                _TR_WIRE,
                {"trace_id": span.trace_id, "span_id": span.span_id},
                payload,
            )
        with flight.site("serve_llm"), flight.trace(
            ctx["trace_id"] if ctx else None
        ):
            self._group.send(payload, self._peer, tag=tag)
        if span is not None:
            tracing.finish(span)

    def pop(self, seq: int, *, timeout: float = 60.0) -> np.ndarray:
        tag = f"kvblk:p{self.epoch}:e{self._src}:{self._dst}:{seq}"
        started = time.monotonic()
        with flight.site("serve_llm"):
            payload = self._group.recv(
                self._peer, tag=tag, timeout=timeout,
            )
        if (
            isinstance(payload, tuple) and len(payload) == 3
            and payload[0] == _TR_WIRE
        ):
            _, ctx, payload = payload
            self.last_trace = ctx
            wait_s = time.monotonic() - started
            end_ns = time.time_ns()
            tracing.emit(
                "channel.pop", ctx,
                start_ns=end_ns - int(wait_s * 1e9), end_ns=end_ns,
                channel=tag, family="kv_wire", seq=seq,
            )
        else:
            self.last_trace = None
        return decode_kv_blocks(payload)

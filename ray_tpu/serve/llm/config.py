"""LLMConfig — every knob of the serve_llm layer in one dataclass.

One config object flows driver → deployment init → prefill/decode
replicas (as a plain dict through serve's init_args, so it survives the
actor wire without custom serialization).
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Optional


@dataclass
class LLMConfig:
    """Knobs for the continuous-batching engine and the KV handoff.

    The defaults describe a toy deterministic LM sized so the whole
    serving path (admission, paged KV, bucketed decode, eviction) runs
    at full fidelity on CPU twins; a real model plugs in through
    ``deployments.LLMPrefill``/``LLMDecode`` subclasses overriding the
    model hooks.
    """

    model_id: str = "toy"
    vocab_size: int = 32000

    # -- KV geometry ----------------------------------------------------
    # Floats of KV state per prompt token, paged into fixed-size blocks
    # (block_tokens tokens/block) in the decode replica's KVBlockPool.
    kv_dim: int = 16
    block_tokens: int = 16
    num_kv_blocks: int = 4096

    # -- continuous batching --------------------------------------------
    # max_slots bounds the running batch; slot_buckets are the padded
    # batch shapes the decode step compiles for (admitted count rounds
    # up to the smallest covering bucket, so recompilation is bounded by
    # len(slot_buckets) instead of one shape per occupancy).
    max_slots: int = 64
    slot_buckets: tuple = (8, 16, 32, 64)
    # Admission queue bound: sequences waiting for a free slot. Beyond
    # it the engine sheds fast (503 + Retry-After at the proxy).
    max_queued_seqs: int = 256
    max_tokens_default: int = 8
    # Idle wait (seconds) on the admission channel when the running
    # batch is non-empty — bounds per-iteration admission latency
    # without spinning a hot loop on an idle engine.
    admit_poll_s: float = 0.002

    # -- KV wire (prefill → decode) -------------------------------------
    # Block-scaled quantized wire via the PR-7 codec; None is the exact-
    # wire fallback knob (ISSUE 17 tentpole b).
    kv_wire_quantize: Optional[str] = "int8"
    kv_wire_block: int = 64

    # -- synthetic compute (bench realism knobs) ------------------------
    prefill_flops: int = 0
    decode_flops: int = 0

    # -- sequence observability (ISSUE 19) ------------------------------
    # Fraction of sequences that get full trace continuity (spans +
    # per-sequence timeline records). The decision is a deterministic
    # hash of request_id, so a replayed sequence keeps its sampling fate
    # (and its trace id) across replica deaths. 0.0 disables the traced
    # path entirely; the token ledger and TTFT/TPOT histograms are
    # always on (they are O(1) arithmetic per token).
    seq_trace_sample: float = 1.0

    # -- multiplexing ---------------------------------------------------
    max_models_per_replica: int = 3

    def wire_config(self):
        """CollectiveConfig for the KV wire, or None for the exact wire.
        Error feedback stays off: a KV handoff is one-shot, so residual
        carry-over would correct nothing (quantization.py's own rule)."""
        if not self.kv_wire_quantize:
            return None
        from ray_tpu.util.collective.quantization import CollectiveConfig

        return CollectiveConfig(
            quantize=self.kv_wire_quantize,
            block_size=self.kv_wire_block,
            error_feedback=False,
        )

    def to_dict(self) -> dict:
        d = asdict(self)
        d["slot_buckets"] = list(self.slot_buckets)
        return d

    @classmethod
    def from_any(cls, value) -> "LLMConfig":
        if isinstance(value, LLMConfig):
            return value
        if value is None:
            return cls()
        known = {
            k: v for k, v in dict(value).items()
            if k in cls.__dataclass_fields__
        }
        if "slot_buckets" in known:
            known["slot_buckets"] = tuple(known["slot_buckets"])
        return cls(**known)

"""Fused RMSNorm Pallas kernel (+ jax reference).

One VMEM pass instead of separate square/mean/rsqrt/mul HLOs — the classic
HBM-bandwidth fusion (SURVEY 'HBM bandwidth' guidance). Falls back to
interpreter mode off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _rmsnorm_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[:] = (x * jax.lax.rsqrt(var + eps) * w_ref[:].astype(jnp.float32)).astype(
        o_ref.dtype
    )


@functools.partial(jax.jit, static_argnames=("eps", "block_rows", "interpret"))
def rmsnorm(
    x: jax.Array,
    weight: jax.Array,
    *,
    eps: float = 1e-6,
    block_rows: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """x: [..., dim]; weight: [dim]."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    orig_shape = x.shape
    dim = orig_shape[-1]
    rows = x.size // dim
    xr = x.reshape(rows, dim)
    block_rows = min(block_rows, rows)
    if rows % block_rows != 0:
        # Odd row counts: plain jax fallback keeps semantics.
        return rmsnorm_reference(x, weight, eps=eps)
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, dim), lambda i: (i, 0)),
            pl.BlockSpec((dim,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, dim), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, dim), x.dtype),
        interpret=interpret,
    )(xr, weight)
    return out.reshape(orig_shape)


def rmsnorm_reference(x: jax.Array, weight: jax.Array, *, eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps) * weight.astype(jnp.float32)).astype(x.dtype)

"""Flash attention as Pallas TPU kernels — forward AND backward.

The hot op of the flagship models (SURVEY §2.9 SP row: the reference has no
native attention kernels at all — attention arrives via user engines; here it
is in-tree). Blocked online-softmax attention:

  forward:  grid = (batch*heads, q_blocks, kv_blocks)   # kv sequential
            VMEM scratch carries running max/sum/accumulator across kv steps;
            emits O and the logsumexp (LSE) residual.
  backward: two kernels (the standard flash-v2 split):
              dq:  grid = (batch*heads, q_blocks, kv_blocks)  # kv sequential
              dkv: grid = (batch*heads, kv_blocks, q_blocks)  # q  sequential
            Both recompute P = exp(S - LSE) blockwise from (q, k) — O(S²)
            probabilities are never materialized in HBM, so long sequences
            train in memory linear in S.

MXU discipline: matmul operands stay in the input dtype (bfloat16 on TPU —
the MXU's native multiply) with float32 accumulation via
preferred_element_type; only softmax/statistics math runs in f32 vectors.

Causal block skipping: grid steps whose (q_block, kv_block) tile is entirely
masked skip all compute (≈2× for causal training).

On non-TPU backends the same kernels run in interpreter mode (the CPU twin,
SURVEY §4.4), so tests exercise the identical code path the TPU compiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _mxu(x, precision):
    """Operand dtype for MXU dots: keep bf16 native; honor explicit
    precision requests (tests use Precision.HIGHEST with f32 inputs)."""
    if precision is None and x.dtype == jnp.bfloat16:
        return x
    return x.astype(jnp.float32)


def _tile_needed(causal, causal_offset, q_index, kv_index, block_q, block_k):
    """False only for tiles that the causal mask zeroes entirely."""
    if not causal:
        return True
    return causal_offset + (q_index + 1) * block_q - 1 >= kv_index * block_k


def _masked_scores(q_ref, k_ref, q_index, kv_index, *, scale, causal,
                   block_q, block_k, precision, causal_offset):
    """scale * Q K^T with the causal mask applied — shared by all three
    kernels so forward and backward can never desynchronize."""
    q = _mxu(q_ref[0], precision)                # [block_q, d]
    k = _mxu(k_ref[0], precision)                # [block_k, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    ) * scale                                    # [block_q, block_k] f32
    if causal:
        # causal_offset = seq_k - seq_q aligns queries to the END of the
        # key sequence (decode convention; matches attention_reference's
        # tril(..., seq_k - seq_q)).
        q_pos = (
            causal_offset + q_index * block_q
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        )
        k_pos = kv_index * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return s, q, k


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *, scale,
    causal, block_q, block_k, num_kv_blocks, precision, causal_offset
):
    kv_index = pl.program_id(2)
    q_index = pl.program_id(1)

    @pl.when(kv_index == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Entirely-masked tiles contribute nothing: skip their compute.
    needed = _tile_needed(causal, causal_offset, q_index, kv_index,
                          block_q, block_k)

    @pl.when(needed)
    def _compute():
        s, _, _ = _masked_scores(
            q_ref, k_ref, q_index, kv_index, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, precision=precision,
            causal_offset=causal_offset,
        )

        m_prev = m_scr[:]                        # [block_q, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                   # [block_q, block_k] f32
        correction = jnp.exp(m_prev - m_new)     # [block_q, 1]
        l_scr[:] = correction * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0]                             # [block_k, d]
        acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
            _mxu(p.astype(v.dtype), precision), _mxu(v, precision),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        m_scr[:] = m_new

    @pl.when(kv_index == num_kv_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _flash_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr, *,
    scale, causal, block_q, block_k, num_kv_blocks, precision, causal_offset
):
    kv_index = pl.program_id(2)
    q_index = pl.program_id(1)

    @pl.when(kv_index == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    needed = _tile_needed(causal, causal_offset, q_index, kv_index,
                          block_q, block_k)

    @pl.when(needed)
    def _compute():
        s, _, k = _masked_scores(
            q_ref, k_ref, q_index, kv_index, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, precision=precision,
            causal_offset=causal_offset,
        )
        lse = lse_ref[0]
        p = jnp.exp(s - lse)                     # [block_q, block_k] f32
        do = do_ref[0]
        dp = jax.lax.dot_general(
            _mxu(do, precision), _mxu(v_ref[0], precision),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )                                        # [block_q, block_k]
        delta = delta_ref[0]
        ds = p * (dp - delta) * scale            # f32
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            _mxu(ds.astype(do.dtype), precision), k,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )

    @pl.when(kv_index == num_kv_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, scale, causal, block_q, block_k, num_q_blocks,
    precision, causal_offset
):
    q_index = pl.program_id(2)
    kv_index = pl.program_id(1)

    @pl.when(q_index == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    needed = _tile_needed(causal, causal_offset, q_index, kv_index,
                          block_q, block_k)

    @pl.when(needed)
    def _compute():
        s, q, _ = _masked_scores(
            q_ref, k_ref, q_index, kv_index, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, precision=precision,
            causal_offset=causal_offset,
        )
        lse = lse_ref[0]
        p = jnp.exp(s - lse)
        do = do_ref[0]
        pt = _mxu(p.astype(do.dtype), precision)  # [block_q, block_k]
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            pt, _mxu(do, precision), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )                                        # [block_k, d]
        dp = jax.lax.dot_general(
            _mxu(do, precision), _mxu(v_ref[0], precision),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        delta = delta_ref[0]
        ds = (p * (dp - delta) * scale).astype(do.dtype)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            _mxu(ds, precision), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )                                        # [block_k, d]

    @pl.when(q_index == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool | None = None,
    precision: jax.lax.Precision | None = None,
) -> jax.Array:
    """q,k,v: [batch, heads, seq, head_dim] (kv heads may be fewer: GQA is
    handled by the caller repeating kv heads). Returns same shape as q.

    Fully differentiable with Pallas kernels on BOTH passes: the forward
    saves (q, k, v, out, lse) and the backward recomputes P blockwise —
    attention memory stays O(seq), never O(seq²).

    precision=None keeps the MXU's fast bf16 multiply for bf16 inputs;
    tests pass Precision.HIGHEST for tight reference comparison.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_vjp(q, k, v, causal, float(scale), block_q, block_k,
                      interpret, precision)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_vjp(q, k, v, causal, scale, block_q, block_k, interpret, precision):
    out, _ = _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret, precision=precision,
    )
    return out


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                   precision):
    out, lse = _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret, precision=precision,
    )
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, precision,
                   residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward(
        q, k, v, out, lse, g, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret, precision=precision,
    )


_flash_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _block_sizes(seq_q, seq_k, block_q, block_k):
    # Shrink to the largest power-of-two block that divides the sequence so
    # callers never trip over the default block size (e.g. seq=768 with the
    # 512 default halves to 256).
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    while block_q > 1 and seq_q % block_q:
        block_q //= 2
    while block_k > 1 and seq_k % block_k:
        block_k //= 2
    assert seq_q % block_q == 0 and seq_k % block_k == 0, (
        f"seq lengths ({seq_q},{seq_k}) must divide blocks ({block_q},{block_k})"
    )
    return block_q, block_k


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "block_q", "block_k", "interpret", "precision"
    ),
)
def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool | None = None,
    precision: jax.lax.Precision | None = None,
) -> tuple[jax.Array, jax.Array]:
    batch, heads, seq_q, dim = q.shape
    _, kv_heads, seq_k, _ = k.shape
    assert kv_heads == heads, "repeat kv heads before calling (GQA)"
    if scale is None:
        scale = dim ** -0.5
    block_q, block_k = _block_sizes(seq_q, seq_k, block_q, block_k)
    if interpret is None:
        interpret = _should_interpret()

    bh = batch * heads
    qr = q.reshape(bh, seq_q, dim)
    kr = k.reshape(bh, seq_k, dim)
    vr = v.reshape(bh, seq_k, dim)
    num_q_blocks = seq_q // block_q
    num_kv_blocks = seq_k // block_k

    kernel = functools.partial(
        _flash_fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        num_kv_blocks=num_kv_blocks,
        precision=precision,
        causal_offset=seq_k - seq_q,
    )
    from jax.experimental.pallas import tpu as pltpu

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, num_q_blocks, num_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, dim), lambda i, j, kv: (i, j, 0)),
            pl.BlockSpec((1, block_k, dim), lambda i, j, kv: (i, kv, 0)),
            pl.BlockSpec((1, block_k, dim), lambda i, j, kv: (i, kv, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dim), lambda i, j, kv: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kv: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, dim), q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),    # running max
            pltpu.VMEM((block_q, 1), jnp.float32),    # running sum
            pltpu.VMEM((block_q, dim), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(batch, heads, seq_q, dim), lse.reshape(
        batch, heads, seq_q
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "block_q", "block_k", "interpret", "precision"
    ),
)
def _flash_backward(
    q, k, v, out, lse, g, *, causal, scale, block_q, block_k, interpret,
    precision
):
    batch, heads, seq_q, dim = q.shape
    seq_k = k.shape[2]
    block_q, block_k = _block_sizes(seq_q, seq_k, block_q, block_k)
    if interpret is None:
        interpret = _should_interpret()

    bh = batch * heads
    qr = q.reshape(bh, seq_q, dim)
    kr = k.reshape(bh, seq_k, dim)
    vr = v.reshape(bh, seq_k, dim)
    dor = g.astype(q.dtype).reshape(bh, seq_q, dim)
    lser = lse.reshape(bh, seq_q, 1)
    # delta_i = rowsum(dO_i ⊙ O_i): tiny elementwise pass, XLA fuses it.
    delta = jnp.sum(
        dor.astype(jnp.float32) * out.reshape(bh, seq_q, dim).astype(
            jnp.float32
        ),
        axis=-1,
        keepdims=True,
    )
    num_q_blocks = seq_q // block_q
    num_kv_blocks = seq_k // block_k
    causal_offset = seq_k - seq_q

    from jax.experimental.pallas import tpu as pltpu

    dq_kernel = functools.partial(
        _flash_dq_kernel,
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        num_kv_blocks=num_kv_blocks, precision=precision,
        causal_offset=causal_offset,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, num_q_blocks, num_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, dim), lambda i, j, kv: (i, j, 0)),
            pl.BlockSpec((1, block_k, dim), lambda i, j, kv: (i, kv, 0)),
            pl.BlockSpec((1, block_k, dim), lambda i, j, kv: (i, kv, 0)),
            pl.BlockSpec((1, block_q, dim), lambda i, j, kv: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kv: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kv: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dim), lambda i, j, kv: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, dim), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, dim), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)

    dkv_kernel = functools.partial(
        _flash_dkv_kernel,
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        num_q_blocks=num_q_blocks, precision=precision,
        causal_offset=causal_offset,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, num_kv_blocks, num_q_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, dim), lambda i, j, qi: (i, qi, 0)),
            pl.BlockSpec((1, block_k, dim), lambda i, j, qi: (i, j, 0)),
            pl.BlockSpec((1, block_k, dim), lambda i, j, qi: (i, j, 0)),
            pl.BlockSpec((1, block_q, dim), lambda i, j, qi: (i, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, qi: (i, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j, qi: (i, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, dim), lambda i, j, qi: (i, j, 0)),
            pl.BlockSpec((1, block_k, dim), lambda i, j, qi: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_k, dim), k.dtype),
            jax.ShapeDtypeStruct((bh, seq_k, dim), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, dim), jnp.float32),
            pltpu.VMEM((block_k, dim), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta)

    shape = (batch, heads, seq_q, dim)
    kshape = (batch, heads, seq_k, dim)
    return (
        dq.reshape(shape),
        dk.reshape(kshape).astype(k.dtype),
        dv.reshape(kshape).astype(v.dtype),
    )


def attention_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = True,
    scale: float | None = None
) -> jax.Array:
    """Pure-jax reference used for kernel numerics tests."""
    dim = q.shape[-1]
    if scale is None:
        scale = dim ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((seq_q, seq_k), dtype=bool), seq_k - seq_q)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)

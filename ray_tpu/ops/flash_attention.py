"""Flash attention as a Pallas TPU kernel.

The hot op of the flagship models (SURVEY §2.9 SP row: the reference has no
native attention kernels at all — attention arrives via user engines; here it
is in-tree). Blocked online-softmax attention:

  grid = (batch*heads, q_blocks, kv_blocks)   # last dim sequential on TPU
  VMEM scratch carries the running max/sum/accumulator across kv steps.

On non-TPU backends the same kernel runs in interpreter mode (the CPU twin,
SURVEY §4.4), so tests exercise the identical code path the TPU compiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, scale, causal,
    block_q, block_k, num_kv_blocks, precision, causal_offset
):
    kv_index = pl.program_id(2)

    @pl.when(kv_index == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32)            # [block_q, d]
    k = k_ref[0].astype(jnp.float32)            # [block_k, d]
    v = v_ref[0].astype(jnp.float32)            # [block_k, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        precision=precision,
    ) * scale                                    # [block_q, block_k]

    if causal:
        q_index = pl.program_id(1)
        # causal_offset = seq_k - seq_q aligns queries to the END of the key
        # sequence (decode convention; matches attention_reference's
        # tril(..., seq_k - seq_q)).
        q_pos = causal_offset + q_index * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kv_index * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

    m_prev = m_scr[:]                            # [block_q, 1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)                       # [block_q, block_k]
    correction = jnp.exp(m_prev - m_new)         # [block_q, 1]
    l_new = correction * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        precision=precision,
    )
    m_scr[:] = m_new
    l_scr[:] = l_new

    @pl.when(kv_index == num_kv_blocks - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
    precision: jax.lax.Precision | None = None,
) -> jax.Array:
    """q,k,v: [batch, heads, seq, head_dim] (kv heads may be fewer: GQA is
    handled by the caller repeating kv heads). Returns same shape as q.

    Differentiable: forward is the Pallas kernel; backward recomputes
    attention in plain jax (flash-style recompute trades FLOPs for the O(S²)
    probs it never stored). precision=None keeps the MXU's fast bf16
    multiply; tests pass Precision.HIGHEST for tight reference comparison.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_vjp(q, k, v, causal, float(scale), block_q, block_k,
                      interpret, precision)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_vjp(q, k, v, causal, scale, block_q, block_k, interpret, precision):
    return _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret, precision=precision,
    )


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret, precision):
    out = _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret, precision=precision,
    )
    return out, (q, k, v)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, precision,
                   residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(
            q_, k_, v_, causal=causal, scale=scale
        ),
        q, k, v,
    )
    return vjp(g.astype(q.dtype))


_flash_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret", "precision"),
)
def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
    precision: jax.lax.Precision | None = None,
) -> jax.Array:
    batch, heads, seq_q, dim = q.shape
    _, kv_heads, seq_k, _ = k.shape
    assert kv_heads == heads, "repeat kv heads before calling (GQA)"
    if scale is None:
        scale = dim ** -0.5
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    assert seq_q % block_q == 0 and seq_k % block_k == 0, (
        f"seq lengths ({seq_q},{seq_k}) must divide blocks ({block_q},{block_k})"
    )
    if interpret is None:
        interpret = _should_interpret()

    bh = batch * heads
    qr = q.reshape(bh, seq_q, dim)
    kr = k.reshape(bh, seq_k, dim)
    vr = v.reshape(bh, seq_k, dim)
    num_q_blocks = seq_q // block_q
    num_kv_blocks = seq_k // block_k

    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        num_kv_blocks=num_kv_blocks,
        precision=precision,
        causal_offset=seq_k - seq_q,
    )
    from jax.experimental.pallas import tpu as pltpu

    out = pl.pallas_call(
        kernel,
        grid=(bh, num_q_blocks, num_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, dim), lambda i, j, kv: (i, j, 0)),
            pl.BlockSpec((1, block_k, dim), lambda i, j, kv: (i, kv, 0)),
            pl.BlockSpec((1, block_k, dim), lambda i, j, kv: (i, kv, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dim), lambda i, j, kv: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, dim), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),    # running max
            pltpu.VMEM((block_q, 1), jnp.float32),    # running sum
            pltpu.VMEM((block_q, dim), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(batch, heads, seq_q, dim)


def attention_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = True,
    scale: float | None = None
) -> jax.Array:
    """Pure-jax reference used for kernel numerics tests."""
    dim = q.shape[-1]
    if scale is None:
        scale = dim ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((seq_q, seq_k), dtype=bool), seq_k - seq_q)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)

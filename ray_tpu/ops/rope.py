"""Rotary position embeddings (RoPE).

Pure jax — XLA fuses this into the surrounding attention ops; a Pallas
kernel buys nothing here (elementwise, bandwidth-bound, already fused).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_frequencies(
    head_dim: int, max_seq: int, theta: float = 10000.0
) -> tuple[jax.Array, jax.Array]:
    """Returns (cos, sin) tables of shape [max_seq, head_dim // 2]."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(
    x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array | None = None
) -> jax.Array:
    """x: [batch, heads, seq, head_dim]; cos/sin: [max_seq, head_dim//2].

    positions: optional [batch, seq] absolute positions (for KV-cache decode
    or sequence-parallel shards whose local index != absolute index).
    """
    seq = x.shape[-2]
    if positions is None:
        c = cos[:seq][None, None, :, :]
        s = sin[:seq][None, None, :, :]
    else:
        c = cos[positions][:, None, :, :]
        s = sin[positions][:, None, :, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    rotated = jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
    )
    return rotated.astype(x.dtype)

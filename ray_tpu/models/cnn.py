"""Small conv nets for the vision benchmark configs.

Covers BASELINE configs 1 (Fashion-MNIST CNN) and 3 (ResNet-18/CIFAR-10):
a LeNet-style CNN and a compact ResNet, both pure-jax param-pytree models
(same conventions as models/transformer.py) so they jit/shard with the
same machinery. Convs run in NHWC which XLA maps onto the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp


@dataclass
class CNNConfig:
    num_classes: int = 10
    channels: Sequence[int] = (32, 64)
    hidden: int = 128
    in_channels: int = 1
    image_size: int = 28
    dtype: object = jnp.float32


def _conv_init(key, kh, kw, cin, cout, dtype):
    scale = jnp.sqrt(2.0 / (kh * kw * cin))
    return {
        "w": (jax.random.normal(key, (kh, kw, cin, cout)) * scale).astype(dtype),
        "b": jnp.zeros((cout,), dtype),
    }


def init_cnn(config: CNNConfig, key: jax.Array) -> dict:
    keys = jax.random.split(key, len(config.channels) + 2)
    params = {"convs": [], "dense": {}, "out": {}}
    cin = config.in_channels
    for i, cout in enumerate(config.channels):
        params["convs"].append(_conv_init(keys[i], 3, 3, cin, cout, config.dtype))
        cin = cout
    spatial = config.image_size // (2 ** len(config.channels))
    flat = spatial * spatial * cin
    scale = jnp.sqrt(2.0 / flat)
    params["dense"] = {
        "w": (jax.random.normal(keys[-2], (flat, config.hidden)) * scale).astype(
            config.dtype
        ),
        "b": jnp.zeros((config.hidden,), config.dtype),
    }
    scale = jnp.sqrt(2.0 / config.hidden)
    params["out"] = {
        "w": (
            jax.random.normal(keys[-1], (config.hidden, config.num_classes)) * scale
        ).astype(config.dtype),
        "b": jnp.zeros((config.num_classes,), config.dtype),
    }
    return params


def cnn_forward(params: dict, images: jax.Array, config: CNNConfig) -> jax.Array:
    """images: [B, H, W, C] → logits [B, num_classes]."""
    x = images
    for conv in params["convs"]:
        x = jax.lax.conv_general_dilated(
            x, conv["w"], window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + conv["b"]
        x = jax.nn.relu(x)
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        )
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["dense"]["w"] + params["dense"]["b"])
    return x @ params["out"]["w"] + params["out"]["b"]


def cnn_loss(params: dict, images, labels, config: CNNConfig):
    logits = cnn_forward(params, images, config)
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
    accuracy = jnp.mean(jnp.argmax(logits, -1) == labels)
    return loss, accuracy


# ---- compact ResNet (CIFAR-scale ResNet-18 stand-in) ----

@dataclass
class ResNetConfig:
    num_classes: int = 10
    width: int = 64
    blocks_per_stage: Sequence[int] = (2, 2, 2, 2)  # ResNet-18 layout
    in_channels: int = 3
    image_size: int = 32
    dtype: object = jnp.float32


def init_resnet(config: ResNetConfig, key: jax.Array) -> dict:
    n_blocks = sum(config.blocks_per_stage)
    keys = iter(jax.random.split(key, 2 * n_blocks + n_blocks + 3))
    params = {"stem": _conv_init(next(keys), 3, 3, config.in_channels,
                                 config.width, config.dtype), "stages": []}
    cin = config.width
    for stage, blocks in enumerate(config.blocks_per_stage):
        cout = config.width * (2 ** stage)
        stage_params = []
        for b in range(blocks):
            block = {
                "conv1": _conv_init(next(keys), 3, 3, cin, cout, config.dtype),
                "conv2": _conv_init(next(keys), 3, 3, cout, cout, config.dtype),
            }
            if cin != cout:
                block["proj"] = _conv_init(next(keys), 1, 1, cin, cout, config.dtype)
            stage_params.append(block)
            cin = cout
        params["stages"].append(stage_params)
    scale = jnp.sqrt(2.0 / cin)
    params["head"] = {
        "w": (jax.random.normal(next(keys), (cin, config.num_classes)) * scale
              ).astype(config.dtype),
        "b": jnp.zeros((config.num_classes,), config.dtype),
    }
    return params


def _conv(x, p, stride=1):
    return jax.lax.conv_general_dilated(
        x, p["w"], window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ) + p["b"]


def resnet_forward(params: dict, images, config: ResNetConfig):
    x = jax.nn.relu(_conv(images, params["stem"]))
    for stage_idx, stage in enumerate(params["stages"]):
        for block_idx, block in enumerate(stage):
            stride = 2 if (stage_idx > 0 and block_idx == 0) else 1
            shortcut = x
            h = jax.nn.relu(_conv(x, block["conv1"], stride))
            h = _conv(h, block["conv2"])
            if "proj" in block:
                shortcut = _conv(shortcut, block["proj"], stride)
            elif stride != 1:
                shortcut = shortcut[:, ::stride, ::stride, :]
            x = jax.nn.relu(h + shortcut)
    x = jnp.mean(x, axis=(1, 2))
    return x @ params["head"]["w"] + params["head"]["b"]


def resnet_loss(params, images, labels, config: ResNetConfig):
    logits = resnet_forward(params, images, config)
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
    accuracy = jnp.mean(jnp.argmax(logits, -1) == labels)
    return loss, accuracy

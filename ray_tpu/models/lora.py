"""LoRA — low-rank adapters for the flagship transformer.

Fills the role the reference reaches via DeepSpeed/PEFT through its Torch
integration shims (SURVEY §2.9 "integration-delegated"): here first-class.
Adapters target the attention projections (wq/wv by default, per the LoRA
paper): effective W = W + (alpha/r)·A@B with A:[d_in,r], B:[r,d_out].
Only adapters train — the frozen base params can stay bfloat16 and fully
sharded while the tiny A/B pytree is what the optimizer touches (the
memory shape that makes multi-host Llama-2-7B LoRA cheap, BASELINE
config 5).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import jax
import jax.numpy as jnp

from ray_tpu.models.transformer import TransformerConfig, forward


@dataclass
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Sequence[str] = ("wq", "wv")


def init_lora(
    model_config: TransformerConfig, lora_config: LoRAConfig, key: jax.Array
) -> dict:
    """A ~ N(0, 1/r), B = 0 → adapters start as identity (paper init)."""
    d = model_config.dim
    hd = model_config.head_dim
    out_dims = {
        "wq": model_config.n_heads * hd,
        "wk": model_config.n_kv_heads * hd,
        "wv": model_config.n_kv_heads * hd,
        "wo": d,
    }
    nl = model_config.n_layers
    r = lora_config.rank
    adapters = {}
    keys = iter(jax.random.split(key, len(lora_config.targets)))
    for target in lora_config.targets:
        d_in = out_dims["wo"] if target == "wo" else d
        d_out = out_dims[target]
        adapters[target] = {
            "a": jax.random.normal(next(keys), (nl, d_in, r), jnp.float32)
            * (1.0 / r),
            "b": jnp.zeros((nl, r, d_out), jnp.float32),
        }
    return adapters


def merge_lora(params: dict, adapters: dict, lora_config: LoRAConfig) -> dict:
    """Base params with adapters folded in: W += (alpha/r)·A@B.
    Used for inference export; training applies adapters unmerged."""
    scale = lora_config.alpha / lora_config.rank
    merged_layers = dict(params["layers"])
    for target, ab in adapters.items():
        delta = jnp.einsum("lir,lro->lio", ab["a"], ab["b"]) * scale
        merged_layers[target] = params["layers"][target] + delta.astype(
            params["layers"][target].dtype
        )
    return {**params, "layers": merged_layers}


def lora_forward(
    params: dict,
    adapters: dict,
    tokens: jax.Array,
    config: TransformerConfig,
    lora_config: LoRAConfig,
):
    """Forward with adapters applied (unmerged: base stays frozen).

    Implementation note: the transformer's layer scan consumes stacked
    [layer, ...] weights, so applying LoRA = adding the per-layer low-rank
    delta to the stacked weight before the scan. XLA fuses the einsum into
    the surrounding graph; the base weight tensor itself is not updated
    (stop_gradient), so grads flow only to A/B.
    """
    frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
    effective = merge_lora(frozen, adapters, lora_config)
    return forward(effective, tokens, config)


def lora_loss(
    params: dict,
    adapters: dict,
    tokens: jax.Array,
    config: TransformerConfig,
    lora_config: LoRAConfig,
):
    """Next-token cross entropy, differentiating w.r.t. adapters only."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = lora_forward(params, adapters, inputs, config, lora_config)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def num_lora_params(adapters: dict) -> int:
    return sum(
        leaf.size for leaf in jax.tree_util.tree_leaves(adapters)
    )

"""Flagship model: LLaMA-style decoder-only transformer, TPU-first.

Design notes (SURVEY §7.0.3 "parallelism is mesh axes"):
  * functional: params are a pytree of jnp arrays; every leaf has a logical
    dim annotation in PARAM_LOGICAL_DIMS, so DP/FSDP/TP/EP sharding is one
    LogicalRules switchboard away — model code never mentions mesh axes.
  * layers are scanned (lax.scan over stacked layer params): O(1) compile
    time in depth, XLA-friendly control flow.
  * attention = in-tree Pallas flash kernel (ops/flash_attention.py); ring /
    Ulysses sequence parallelism plug in via `attention_fn` (parallel/).
  * MoE blocks use dense dispatch/combine einsums with an "expert" logical
    dim — under pjit, GSPMD partitions the expert matmuls over the ep axis
    and inserts the token all_to_alls (first-class EP, which the reference
    lacks entirely — SURVEY §2.9).
  * weights default to bfloat16 (MXU-native); norms/softmax accumulate f32.

Reference parity: the reference has no model zoo of its own (models arrive
via torch); this model family is the TPU build's equivalent of the LLM
examples the reference runs through vLLM/DeepSpeed integrations.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ray_tpu.ops.flash_attention import attention_reference, flash_attention
from ray_tpu.ops.rmsnorm import rmsnorm_reference
from ray_tpu.ops.rope import apply_rope, rope_frequencies


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 11008
    max_seq: int = 4096
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    moe: MoEConfig | None = None
    # "flash" | "reference" | callable(q,k,v,causal)->o supplied by
    # parallel/ (ring attention, ulysses).
    attention: str = "flash"
    # Rematerialization policy for the layer scan: None (save everything),
    # "dots" (save matmul outputs only), "full" (save nothing — recompute
    # the whole layer in backward). Trades HBM for FLOPs (SURVEY §7.0 HBM
    # bullet); pick per chip memory at bench/train-config level.
    remat: str | None = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def tiny(**overrides) -> "TransformerConfig":
        base = dict(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            hidden_dim=128, max_seq=128, dtype=jnp.float32,
        )
        base.update(overrides)
        return TransformerConfig(**base)

    @staticmethod
    def llama2_7b(**overrides) -> "TransformerConfig":
        base = dict(
            vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=32, hidden_dim=11008, max_seq=4096,
        )
        base.update(overrides)
        return TransformerConfig(**base)

    @staticmethod
    def llama_1b(**overrides) -> "TransformerConfig":
        """~1.2B params (16 layers × 67M + 131M embed/head) — the smallest
        config a replicated f32 train state (params+grads+Adam ≈ 19 GB)
        cannot fit on one 16 GB chip, and the fit-at-1B release gate's
        subject. Shapes keep every shardable dim divisible by 8 so any
        (dp, fsdp, tp) factorization of a v4-8 slice tiles evenly."""
        base = dict(
            vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
            n_kv_heads=16, hidden_dim=8192, max_seq=2048, remat="dots",
        )
        base.update(overrides)
        return TransformerConfig(**base)


# Logical dim names per param leaf (layer-stacked leaves lead with "layer").
def param_logical_dims(config: TransformerConfig) -> dict:
    dense_mlp = {
        "w_gate": ("layer", "embed", "mlp"),
        "w_up": ("layer", "embed", "mlp"),
        "w_down": ("layer", "mlp", "embed"),
    }
    moe_mlp = {
        "router": ("layer", "embed", None),
        "w_gate": ("layer", "expert", "embed", "mlp"),
        "w_up": ("layer", "expert", "embed", "mlp"),
        "w_down": ("layer", "expert", "mlp", "embed"),
    }
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layer", None),
            "wq": ("layer", "embed", "heads"),
            "wk": ("layer", "embed", "kv"),
            "wv": ("layer", "embed", "kv"),
            "wo": ("layer", "heads", "embed"),
            "mlp_norm": ("layer", None),
            **(moe_mlp if config.moe else dense_mlp),
        },
        "final_norm": (None,),
        "lm_head": ("embed", "vocab"),
    }


def init_params(config: TransformerConfig, key: jax.Array) -> dict:
    keys = iter(jax.random.split(key, 16))
    dt = config.dtype
    d, hd = config.dim, config.head_dim
    nl = config.n_layers
    q_out = config.n_heads * hd
    kv_out = config.n_kv_heads * hd

    def dense(key, *shape, scale=None):
        scale = scale if scale is not None else shape[-2] ** -0.5
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dt)

    if config.moe:
        experts = config.moe.num_experts
        mlp = {
            "router": dense(next(keys), nl, d, experts).astype(jnp.float32),
            "w_gate": dense(next(keys), nl, experts, d, config.hidden_dim),
            "w_up": dense(next(keys), nl, experts, d, config.hidden_dim),
            "w_down": dense(
                next(keys), nl, experts, config.hidden_dim, d,
                scale=config.hidden_dim ** -0.5,
            ),
        }
    else:
        mlp = {
            "w_gate": dense(next(keys), nl, d, config.hidden_dim),
            "w_up": dense(next(keys), nl, d, config.hidden_dim),
            "w_down": dense(
                next(keys), nl, config.hidden_dim, d,
                scale=config.hidden_dim ** -0.5,
            ),
        }
    return {
        "embed": dense(next(keys), config.vocab_size, d, scale=0.02),
        "layers": {
            "attn_norm": jnp.ones((nl, d), dt),
            "wq": dense(next(keys), nl, d, q_out),
            "wk": dense(next(keys), nl, d, kv_out),
            "wv": dense(next(keys), nl, d, kv_out),
            "wo": dense(next(keys), nl, q_out, d, scale=q_out ** -0.5),
            "mlp_norm": jnp.ones((nl, d), dt),
            **mlp,
        },
        "final_norm": jnp.ones((d,), dt),
        "lm_head": dense(next(keys), d, config.vocab_size, scale=d ** -0.5),
    }


def _attention_impl(config: TransformerConfig) -> Callable:
    if callable(config.attention):
        return config.attention
    if config.attention == "flash":
        return lambda q, k, v, causal: flash_attention(q, k, v, causal=causal)
    return lambda q, k, v, causal: attention_reference(q, k, v, causal=causal)


def _repeat_kv(x: jax.Array, repeats: int) -> jax.Array:
    if repeats == 1:
        return x
    return jnp.repeat(x, repeats, axis=1)


def _attention_block(x, layer, config, cos_sin, positions, attention_fn):
    batch, seq, d = x.shape
    hd = config.head_dim
    h = _rmsnorm_ckpt(x, layer["attn_norm"])
    q = (h @ layer["wq"]).reshape(batch, seq, config.n_heads, hd)
    k = (h @ layer["wk"]).reshape(batch, seq, config.n_kv_heads, hd)
    v = (h @ layer["wv"]).reshape(batch, seq, config.n_kv_heads, hd)
    q = q.transpose(0, 2, 1, 3)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    cos, sin = cos_sin
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    rep = config.n_heads // config.n_kv_heads
    o = attention_fn(q, _repeat_kv(k, rep), _repeat_kv(v, rep), True)
    o = o.transpose(0, 2, 1, 3).reshape(batch, seq, config.n_heads * hd)
    return x + (o @ layer["wo"]).astype(x.dtype)


@functools.partial(jax.checkpoint, prevent_cse=False)
def _silu_mul(gate, up):
    """silu(gate) * up with f32 math but bf16 residency.

    jax.checkpoint (nothing saveable) means backward re-derives the f32
    intermediates from the bf16 `gate`/`up` dot outputs instead of XLA
    keeping 4-byte copies of the hidden activations alive across the whole
    layer stack — measured 2×2.06 GB saved per 8-layer/12×1024-token step
    on v5e, for a recompute cost that is pure VPU elementwise.
    """
    act = jax.nn.silu(gate.astype(jnp.float32))
    return (act * up.astype(jnp.float32)).astype(gate.dtype)


# Same trick for the norm: backward recomputes the f32 normalize from the
# bf16 input instead of saving the f32 normalized tensor per layer.
# prevent_cse=False on both: these only run under lax.scan, where the CSE
# barriers are unnecessary and would block epilogue fusion.
_rmsnorm_ckpt = jax.checkpoint(rmsnorm_reference, prevent_cse=False)


def _dense_mlp(h, layer):
    # silu math in f32 for accuracy but residuals stored in the model dtype
    # (bf16): halves the dominant activation-memory term vs keeping the
    # f32 intermediates live for backward.
    gate = (h @ layer["w_gate"]).astype(h.dtype)
    up = (h @ layer["w_up"]).astype(h.dtype)
    return _silu_mul(gate, up) @ layer["w_down"]


def _moe_mlp(h, layer, config: TransformerConfig):
    """Dense dispatch/combine MoE (Mesh-TF style). Static shapes via
    capacity buckets; expert dim carries the "expert" logical annotation so
    GSPMD shards the expert matmuls over ep and inserts all_to_alls."""
    moe = config.moe
    batch, seq, d = h.shape
    tokens = batch * seq
    ht = h.reshape(tokens, d)
    logits = (ht.astype(jnp.float32) @ layer["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)              # [T, E]
    capacity = max(
        1, int(moe.capacity_factor * moe.top_k * tokens / moe.num_experts)
    )

    combine = jnp.zeros((tokens, moe.num_experts, capacity), jnp.float32)
    remaining = probs
    # Per-expert slots already claimed by earlier top-k iterations: a token's
    # 2nd-choice position must start AFTER every 1st-choice pick for that
    # expert (GShard-style offset), or slots collide and tokens get summed.
    occupancy = jnp.zeros((moe.num_experts,), jnp.float32)
    for _ in range(moe.top_k):
        gate, choice = jnp.max(remaining, axis=-1), jnp.argmax(remaining, axis=-1)
        onehot = jax.nn.one_hot(choice, moe.num_experts, dtype=jnp.float32)
        position = (
            jnp.cumsum(onehot, axis=0) - 1.0 + occupancy[None, :]
        ) * onehot
        pos_idx = jnp.sum(position, axis=-1).astype(jnp.int32)
        keep = pos_idx < capacity
        slot = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)
        contribution = (
            gate[:, None, None] * keep[:, None, None]
            * onehot[:, :, None] * slot[:, None, :]
        )
        combine = combine + contribution
        occupancy = occupancy + jnp.sum(onehot, axis=0)
        remaining = remaining * (1.0 - onehot)
    dispatch = (combine > 0).astype(h.dtype)             # [T, E, C]

    expert_in = jnp.einsum("tec,td->ecd", dispatch, ht)  # [E, C, D]
    gate_o = jnp.einsum("ecd,edm->ecm", expert_in, layer["w_gate"]).astype(h.dtype)
    up_o = jnp.einsum("ecd,edm->ecm", expert_in, layer["w_up"]).astype(h.dtype)
    expert_out = jnp.einsum(
        "ecm,emd->ecd", _silu_mul(gate_o, up_o), layer["w_down"]
    )
    out = jnp.einsum("tec,ecd->td", combine.astype(h.dtype), expert_out)
    return out.reshape(batch, seq, d)


def forward(
    params: dict,
    tokens: jax.Array,
    config: TransformerConfig,
    positions: jax.Array | None = None,
) -> jax.Array:
    """tokens: [batch, seq] int32 -> logits [batch, seq, vocab] (f32)."""
    attention_fn = _attention_impl(config)
    cos, sin = rope_frequencies(config.head_dim, config.max_seq, config.rope_theta)
    x = params["embed"][tokens]

    def layer_step(carry, layer):
        x = carry
        x = _attention_block(x, layer, config, (cos, sin), positions, attention_fn)
        h = _rmsnorm_ckpt(x, layer["mlp_norm"])
        if config.moe:
            x = x + _moe_mlp(h, layer, config).astype(x.dtype)
        else:
            x = x + _dense_mlp(h, layer).astype(x.dtype)
        return x, None

    if config.remat == "full":
        layer_step = jax.checkpoint(
            layer_step, policy=jax.checkpoint_policies.nothing_saveable
        )
    elif config.remat == "dots":
        layer_step = jax.checkpoint(
            layer_step,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    elif config.remat is not None:
        raise ValueError(f"unknown remat policy {config.remat!r}")

    x, _ = jax.lax.scan(layer_step, x, params["layers"])
    x = rmsnorm_reference(x, params["final_norm"])
    return (x @ params["lm_head"]).astype(jnp.float32)


def logits_loss(
    logits: jax.Array,
    targets: jax.Array,
    mask: jax.Array | None = None,
) -> jax.Array:
    """Token cross-entropy from logits — shared by the fused loss_fn and
    the pipeline's last stage (which receives logits over the wire)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def loss_fn(
    params: dict,
    tokens: jax.Array,
    targets: jax.Array,
    config: TransformerConfig,
    mask: jax.Array | None = None,
) -> jax.Array:
    return logits_loss(forward(params, tokens, config), targets, mask)


def num_params(params: dict) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))


def config_num_params(config: TransformerConfig) -> int:
    """Parameter count from shapes alone — lets the memory-budget check
    refuse a config before any array is materialized."""
    d, hd = config.dim, config.head_dim
    attn = d * hd * (config.n_heads * 2 + config.n_kv_heads * 2)
    if config.moe:
        e = config.moe.num_experts
        mlp = d * e + 3 * e * d * config.hidden_dim
    else:
        mlp = 3 * d * config.hidden_dim
    per_layer = attn + mlp + 2 * d
    return (
        config.n_layers * per_layer
        + 2 * config.vocab_size * d  # embed + lm_head
        + d  # final_norm
    )


# ---------------------------------------------------------------------------
# MPMD pipeline stages (cross-slice form — train._internal.stage_runner)
# ---------------------------------------------------------------------------
def partition_stages(params: dict, config: TransformerConfig, num_stages: int) -> list[dict]:
    """Split a full param tree into ``num_stages`` contiguous layer groups.

    Stage 0 additionally owns the embedding table; the last stage owns the
    final norm + lm_head. Stage trees are disjoint, so per-stage optimizer
    updates compose to exactly the fused update.
    """
    if config.n_layers % num_stages != 0:
        raise ValueError(
            f"n_layers={config.n_layers} not divisible by {num_stages} stages"
        )
    per = config.n_layers // num_stages
    stages = []
    for s in range(num_stages):
        layers = jax.tree.map(
            lambda leaf: leaf[s * per : (s + 1) * per], params["layers"]
        )
        tree = {"layers": layers}
        if s == 0:
            tree["embed"] = params["embed"]
        if s == num_stages - 1:
            tree["final_norm"] = params["final_norm"]
            tree["lm_head"] = params["lm_head"]
        stages.append(tree)
    return stages


def merge_stages(stage_trees: list[dict]) -> dict:
    """Inverse of :func:`partition_stages` — reassemble the fused tree
    (checkpoint save goes through the fused layout so restore works at any
    pipeline factorization, including pp=1)."""
    layers = jax.tree.map(
        lambda *leaves: jnp.concatenate(leaves, axis=0),
        *[t["layers"] for t in stage_trees],
    )
    return {
        "embed": stage_trees[0]["embed"],
        "layers": layers,
        "final_norm": stage_trees[-1]["final_norm"],
        "lm_head": stage_trees[-1]["lm_head"],
    }


def stage_logical_dims(config: TransformerConfig, stage: int, num_stages: int) -> dict:
    """param_logical_dims subset matching one stage's tree shape — so the
    in-stage GSPMD (fsdp/tp inside a pipeline stage) reuses the same rules."""
    full = param_logical_dims(config)
    tree = {"layers": full["layers"]}
    if stage == 0:
        tree["embed"] = full["embed"]
    if stage == num_stages - 1:
        tree["final_norm"] = full["final_norm"]
        tree["lm_head"] = full["lm_head"]
    return tree


def stage_forward(
    stage_params: dict,
    x: jax.Array,
    config: TransformerConfig,
    *,
    first: bool,
    last: bool,
    positions: jax.Array | None = None,
) -> jax.Array:
    """Apply one pipeline stage's layer slice.

    First stage: ``x`` is int tokens [batch, seq] → embeds then runs its
    layers. Interior stages: ``x`` is activations [batch, seq, dim]
    received over the collective p2p plane. Last stage: also applies
    final_norm + lm_head, returning f32 logits.
    """
    attention_fn = _attention_impl(config)
    cos, sin = rope_frequencies(config.head_dim, config.max_seq, config.rope_theta)
    if first:
        x = stage_params["embed"][x]

    def layer_step(carry, layer):
        h_in = carry
        h_in = _attention_block(
            h_in, layer, config, (cos, sin), positions, attention_fn
        )
        h = _rmsnorm_ckpt(h_in, layer["mlp_norm"])
        if config.moe:
            h_in = h_in + _moe_mlp(h, layer, config).astype(h_in.dtype)
        else:
            h_in = h_in + _dense_mlp(h, layer).astype(h_in.dtype)
        return h_in, None

    x, _ = jax.lax.scan(layer_step, x, stage_params["layers"])
    if last:
        x = rmsnorm_reference(x, stage_params["final_norm"])
        x = (x @ stage_params["lm_head"]).astype(jnp.float32)
    return x


# ---------------------------------------------------------------------------
# KV-cache decode (serving path)
# ---------------------------------------------------------------------------
def init_kv_cache(config: TransformerConfig, batch: int, max_seq: int) -> dict:
    hd = config.head_dim
    shape = (config.n_layers, batch, config.n_kv_heads, max_seq, hd)
    return {
        "k": jnp.zeros(shape, config.dtype),
        "v": jnp.zeros(shape, config.dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def decode_step(
    params: dict, cache: dict, tokens: jax.Array, config: TransformerConfig
) -> tuple[jax.Array, dict]:
    """One greedy decode step. tokens: [batch, 1] -> (logits [batch, vocab],
    new cache). Static shapes: cache is a fixed-size ring the XLA compiler
    can tile; `length` is a traced scalar."""
    cos, sin = rope_frequencies(config.head_dim, config.max_seq, config.rope_theta)
    batch = tokens.shape[0]
    hd = config.head_dim
    length = cache["length"]
    positions = jnp.full((batch, 1), length, jnp.int32)
    x = params["embed"][tokens]

    def layer_step(carry, inputs):
        x = carry
        layer, k_cache, v_cache = inputs
        h = rmsnorm_reference(x, layer["attn_norm"])
        q = (h @ layer["wq"]).reshape(batch, 1, config.n_heads, hd).transpose(0, 2, 1, 3)
        k = (h @ layer["wk"]).reshape(batch, 1, config.n_kv_heads, hd).transpose(0, 2, 1, 3)
        v = (h @ layer["wv"]).reshape(batch, 1, config.n_kv_heads, hd).transpose(0, 2, 1, 3)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, length, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, length, 0)
        )
        rep = config.n_heads // config.n_kv_heads
        keys = _repeat_kv(k_cache, rep).astype(jnp.float32)
        vals = _repeat_kv(v_cache, rep).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), keys) * hd ** -0.5
        idx = jnp.arange(keys.shape[2])
        s = jnp.where(idx[None, None, None, :] <= length, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vals)
        o = o.transpose(0, 2, 1, 3).reshape(batch, 1, config.n_heads * hd)
        x = x + (o.astype(x.dtype) @ layer["wo"])
        h2 = rmsnorm_reference(x, layer["mlp_norm"])
        x = x + _dense_mlp(h2, layer).astype(x.dtype)
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rmsnorm_reference(x, params["final_norm"])
    logits = (x[:, 0] @ params["lm_head"]).astype(jnp.float32)
    new_cache = {"k": new_k, "v": new_v, "length": length + 1}
    return logits, new_cache

"""@ray_tpu.remote actor classes.

Role-equivalent of python/ray/actor.py :: ActorClass / ActorHandle /
ActorMethod — remote class instantiation, .options() (name/lifetime/
max_restarts/max_task_retries/max_concurrency/resources/scheduling
strategy), named + detached actors, handle serialization.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any

from ray_tpu import exceptions
from ray_tpu._private import serialization, worker
from ray_tpu._private.ids import ActorID


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._name, args, kwargs, num_returns=1)

    def options(self, *, num_returns: int = 1):
        method = self

        class _Bound:
            def remote(_self, *args, **kwargs):
                return method._handle._invoke(
                    method._name, args, kwargs, num_returns=num_returns
                )

        return _Bound()


class ActorHandle:
    def __init__(self, actor_id: str, methods: list[str], max_task_retries: int = 0):
        self._actor_id = actor_id
        self._methods = set(methods)
        self._max_task_retries = max_task_retries

    def _invoke(self, method: str, args, kwargs, num_returns: int = 1):
        ctx = worker.get_global_context()
        refs = ctx.submit_actor_task(
            self._actor_id,
            method,
            args,
            kwargs,
            num_returns=num_returns,
            max_task_retries=self._max_task_retries,
        )
        return refs[0] if num_returns == 1 else refs

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._methods:
            raise AttributeError(
                f"actor {self._actor_id} has no remote method {name!r}"
            )
        return ActorMethod(self, name)

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, sorted(self._methods), self._max_task_retries),
        )

    def __repr__(self):
        return f"ActorHandle({self._actor_id})"


class ActorClass:
    def __init__(self, cls: type, **default_options):
        self._cls = cls
        self._options = {
            "num_cpus": 1,
            "resources": None,
            "name": None,
            "namespace": None,
            "lifetime": None,
            "max_restarts": 0,
            "max_task_retries": 0,
            "max_concurrency": 1,
            "runtime_env": None,
            "scheduling_strategy": None,
        }
        self._options.update(default_options)
        self._class_id: str | None = None
        self._exported_for: str | None = None  # job id of the exporting cluster
        self._export_lock = threading.Lock()
        self.__name__ = cls.__name__

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class cannot be instantiated directly; use "
            f"{self.__name__}.remote(...)"
        )

    def options(self, **options) -> "ActorClass":
        clone = ActorClass(self._cls, **{**self._options, **options})
        clone._class_id = self._class_id
        clone._exported_for = self._exported_for
        return clone

    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_export_lock", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._export_lock = threading.Lock()
        if "_exported_for" not in self.__dict__:
            self._exported_for = None

    def _ensure_exported(self) -> str:
        # Per-cluster export (see RemoteFunction._ensure_exported): a
        # module-level actor class outlives shutdown()/init() cycles and
        # must re-export into each new cluster's empty function table.
        ctx = worker.get_global_context()
        cluster_key = ctx.job_id
        if self._class_id is not None and self._exported_for == cluster_key:
            return self._class_id
        with self._export_lock:
            if self._class_id is None or self._exported_for != cluster_key:
                raw = serialization.dumps_function(self._cls)
                class_id = "cls-" + hashlib.sha1(raw).hexdigest()[:20]
                ctx.io.run(
                    ctx.controller.call(
                        "kv_put",
                        {
                            "namespace": "funcs",
                            "key": class_id,
                            "value": raw,
                            "overwrite": False,
                            # Content-addressed, so the token needs no
                            # randomness: any retry of this export is the
                            # same logical write.
                            "mutation_token": f"export:{class_id}",
                        },
                    )
                )
                self._class_id = class_id
                self._exported_for = cluster_key
        return self._class_id

    def _public_methods(self) -> list[str]:
        return [
            name
            for name in dir(self._cls)
            if not name.startswith("_") and callable(getattr(self._cls, name))
        ]

    def remote(self, *args, **kwargs) -> ActorHandle:
        ctx = worker.get_global_context()
        class_id = self._ensure_exported()
        opts = self._options
        resources = dict(opts["resources"] or {})
        resources.setdefault("CPU", opts["num_cpus"])
        num_tpus = opts.get("num_tpus")
        if num_tpus:
            resources["TPU"] = num_tpus
        actor_id = ActorID.random()
        creation_args, _ = serialization.serialize((args, kwargs))
        from ray_tpu._private.core_context import _encode_strategy

        spec = {
            "actor_id": actor_id,
            "class_id": class_id,
            "class_name": self.__name__,
            "methods": self._public_methods(),
            "resources": resources,
            "name": opts["name"],
            "namespace": opts["namespace"] or "default",
            "lifetime": opts["lifetime"],
            "max_restarts": opts["max_restarts"],
            "max_task_retries": opts["max_task_retries"],
            "max_concurrency": opts["max_concurrency"],
            "runtime_env": opts["runtime_env"] or {},
            "scheduling_strategy": _encode_strategy(opts["scheduling_strategy"]),
            "job_id": ctx.job_id,
            "submitter_node": ctx.node_id,
            "creation_args": creation_args,
            # Idempotency token: the client-random actor_id uniquely
            # identifies this logical create, so a transport-level retry
            # after a dropped/duplicated reply is applied exactly once.
            "mutation_token": f"create-actor:{actor_id}",
        }
        resp = ctx.io.run(ctx.controller.call("create_actor", spec))
        if resp["status"] == "name_exists":
            raise ValueError(
                f"actor name {opts['name']!r} is already taken"
            )
        return ActorHandle(actor_id, self._public_methods(), opts["max_task_retries"])


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    """Look up a named actor (reference: ray.get_actor)."""
    ctx = worker.get_global_context()
    resp = ctx.io.run(
        ctx.controller.call(
            "get_named_actor", {"name": name, "namespace": namespace}
        )
    )
    if resp["status"] != "ok":
        raise ValueError(f"no actor named {name!r} in namespace {namespace!r}")
    meta = resp["spec_meta"]
    return ActorHandle(
        resp["actor_id"], meta["methods"], meta.get("max_task_retries", 0)
    )

"""In-process multi-node cluster simulation for tests.

Role-equivalent of python/ray/cluster_utils.py :: Cluster — multiple node
agents (each with its own shm store and worker pool) + one controller on a
single machine, with add_node/remove_node for failure testing (the
reference's core multi-node-without-a-cluster trick, SURVEY §4.4.1).
"""

from __future__ import annotations

import time

from ray_tpu._private import worker as _worker
from ray_tpu._private.node import LocalCluster


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: dict | None = None):
        self._cluster = LocalCluster()
        self._nodes: dict[str, object] = {}
        if initialize_head:
            args = head_node_args or {}
            self._cluster.start_head(
                resources=args.get("resources"),
                store_capacity=args.get("object_store_memory", 0),
            )
            self._nodes[self._cluster.head_node_id] = self._cluster.agents[0]

    @property
    def address(self) -> str:
        host, port = self._cluster.controller_addr
        return f"{host}:{port}"

    @property
    def session_dir(self) -> str:
        return self._cluster.session_dir

    def add_node(self, resources: dict | None = None, num_cpus: float | None = None,
                 object_store_memory: int = 0, **kw) -> str:
        merged = dict(resources or {})
        if num_cpus is not None:
            merged["CPU"] = num_cpus
        node_id = self._cluster.add_node(
            resources=merged, store_capacity=object_store_memory
        )
        self._nodes[node_id] = self._cluster.agents[-1]
        return node_id

    def remove_node(self, node_id: str) -> None:
        """Kill a node's agent process (and its workers die with the store)."""
        handle = self._nodes.pop(node_id, None)
        if handle is not None:
            handle.kill()

    def kill_controller(self) -> None:
        """SIGKILL the controller process (control-plane fault injection,
        reference: test_gcs_fault_tolerance.py patterns)."""
        self._cluster.kill_controller()

    def restart_controller(self) -> None:
        """Restart the controller on the same address; it reloads the
        persisted snapshot and the cluster reconnects."""
        self._cluster.restart_controller()

    # -- chaos integration -------------------------------------------------
    @property
    def agent_addrs(self) -> list[tuple]:
        """RPC addresses of every node agent, in start order (chaos
        tooling sends chaos_kill_worker etc. straight to agents)."""
        return list(self._cluster.agent_addrs)

    @property
    def agent_node_ids(self) -> list[str]:
        return list(self._cluster.agent_node_ids)

    def kill_agent(self, index: int) -> None:
        """SIGKILL the index-th node agent's process group (workers die
        with it) without forgetting the node — pair with wait_for_nodes
        after a heal to observe re-registration."""
        self._cluster.agents[index].kill()

    def start_chaos(self, schedule, log_dir: str | None = None):
        """Install a FaultSchedule in this driver process AND the
        environment (future cluster subprocesses inherit it), then start
        a ChaosMonkey executing the schedule's kills against this
        cluster. Returns the started monkey."""
        from ray_tpu.util.chaos import ChaosMonkey, install

        install(schedule, identity="driver", log_dir=log_dir)
        monkey = ChaosMonkey(self, schedule)
        monkey.start()
        return monkey

    def wait_for_nodes(self, expected: int | None = None, timeout: float = 30.0) -> None:
        import ray_tpu

        expected = expected if expected is not None else len(self._nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if len(alive) >= expected:
                return
            time.sleep(0.1)
        raise TimeoutError(f"cluster did not reach {expected} alive nodes")

    def shutdown(self) -> None:
        self._cluster.shutdown()

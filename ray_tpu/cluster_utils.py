"""In-process multi-node cluster simulation for tests.

Role-equivalent of python/ray/cluster_utils.py :: Cluster — multiple node
agents (each with its own shm store and worker pool) + one controller on a
single machine, with add_node/remove_node for failure testing (the
reference's core multi-node-without-a-cluster trick, SURVEY §4.4.1).
"""

from __future__ import annotations

import time

from ray_tpu._private import worker as _worker
from ray_tpu._private.node import LocalCluster


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: dict | None = None):
        self._cluster = LocalCluster()
        self._nodes: dict[str, object] = {}
        if initialize_head:
            args = head_node_args or {}
            self._cluster.start_head(
                resources=args.get("resources"),
                store_capacity=args.get("object_store_memory", 0),
            )
            self._nodes[self._cluster.head_node_id] = self._cluster.agents[0]

    @property
    def address(self) -> str:
        host, port = self._cluster.controller_addr
        return f"{host}:{port}"

    @property
    def session_dir(self) -> str:
        return self._cluster.session_dir

    def add_node(self, resources: dict | None = None, num_cpus: float | None = None,
                 object_store_memory: int = 0, **kw) -> str:
        merged = dict(resources or {})
        if num_cpus is not None:
            merged["CPU"] = num_cpus
        node_id = self._cluster.add_node(
            resources=merged, store_capacity=object_store_memory
        )
        self._nodes[node_id] = self._cluster.agents[-1]
        return node_id

    def remove_node(self, node_id: str) -> None:
        """Kill a node's agent process (and its workers die with the store)."""
        handle = self._nodes.pop(node_id, None)
        if handle is not None:
            handle.kill()

    def kill_controller(self) -> None:
        """SIGKILL the controller process (control-plane fault injection,
        reference: test_gcs_fault_tolerance.py patterns)."""
        self._cluster.kill_controller()

    def restart_controller(self) -> None:
        """Restart the controller on the same address; it reloads the
        persisted snapshot and the cluster reconnects."""
        self._cluster.restart_controller()

    # -- chaos integration -------------------------------------------------
    @property
    def agent_addrs(self) -> list[tuple]:
        """RPC addresses of every node agent, in start order (chaos
        tooling sends chaos_kill_worker etc. straight to agents)."""
        return list(self._cluster.agent_addrs)

    @property
    def agent_node_ids(self) -> list[str]:
        return list(self._cluster.agent_node_ids)

    def kill_agent(self, index: int) -> None:
        """SIGKILL the index-th node agent's process group (workers die
        with it) without forgetting the node — pair with wait_for_nodes
        after a heal to observe re-registration."""
        self._cluster.agents[index].kill()

    def start_chaos(self, schedule, log_dir: str | None = None):
        """Install a FaultSchedule in this driver process AND the
        environment (future cluster subprocesses inherit it), then start
        a ChaosMonkey executing the schedule's kills against this
        cluster. Returns the started monkey."""
        from ray_tpu.util.chaos import ChaosMonkey, install

        install(schedule, identity="driver", log_dir=log_dir)
        monkey = ChaosMonkey(self, schedule)
        monkey.start()
        return monkey

    def wait_for_nodes(self, expected: int | None = None, timeout: float = 30.0) -> None:
        import ray_tpu

        expected = expected if expected is not None else len(self._nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if len(alive) >= expected:
                return
            time.sleep(0.1)
        raise TimeoutError(f"cluster did not reach {expected} alive nodes")

    def shutdown(self) -> None:
        self._cluster.shutdown()


# ---------------------------------------------------------------------------
# Fake-provider scale harness: a REAL controller + N lightweight fake node
# agents in ONE process/loop. Each fake agent is a real RPC server+client
# that registers, heartbeats, and answers the agent-side control RPCs
# (start_actor / prepare_bundle / commit_bundle / release_bundle /
# kill_worker) instantly with honest resource accounting — no worker
# processes, no object stores. This is what lets the scale-envelope suite
# exercise 32+ nodes / 2k actors / 200 PGs / 100k leases on one machine:
# the control-plane code paths are the production ones end to end; only
# the data plane is faked. (Reference: fake_cluster / mock worker
# patterns in Ray's release scalability tests.)
# ---------------------------------------------------------------------------


class FakeNodeAgent:
    """One fake node. Talks the full agent<->controller protocol over the
    real RPC stack; start_actor consumes capacity, kill_worker returns it,
    heartbeats report honest availability plus piggybacked stats."""

    def __init__(self, index: int, controller_addr: tuple,
                 resources: dict | None = None):
        from ray_tpu._private.rpc import RpcClient, RpcServer

        self.index = index
        self.node_id = f"fake-node-{index:04d}"
        self.controller_addr = controller_addr
        self.resources_total = dict(resources or {"CPU": 64.0})
        self.resources_total.setdefault(f"node:{self.node_id}", 1.0)
        self.available = dict(self.resources_total)
        self.server = RpcServer(name=f"fake-agent-{index}")
        self.client = RpcClient(
            tuple(controller_addr), name=f"fake-agent-{index}",
            auto_reconnect=True,
        )
        self.addr: tuple | None = None
        self.workers: dict[str, dict] = {}   # worker_id -> resources
        self.bundles: dict[tuple, dict] = {}  # (pg_id, idx) -> resources
        self._worker_seq = 0
        self._hb_task = None
        self.heartbeats_sent = 0

    # -- agent-side control RPCs (served to the controller) --------------
    def _fits(self, resources: dict) -> bool:
        return all(
            self.available.get(k, 0.0) + 1e-9 >= v
            for k, v in resources.items() if v > 0
        )

    def _consume(self, resources: dict) -> None:
        for k, v in resources.items():
            if v > 0:
                self.available[k] = self.available.get(k, 0.0) - v

    def _restore(self, resources: dict) -> None:
        for k, v in resources.items():
            if v > 0:
                self.available[k] = self.available.get(k, 0.0) + v

    async def rpc_start_actor(self, conn, payload) -> dict:
        resources = (payload.get("spec") or {}).get("resources") or {"CPU": 1}
        if not self._fits(resources):
            return {"status": "busy"}
        self._consume(resources)
        self._worker_seq += 1
        worker_id = f"fw-{self.index:04d}-{self._worker_seq}"
        self.workers[worker_id] = dict(resources)
        return {
            "status": "ok",
            "worker_id": worker_id,
            "pid": 0,
            "worker_addr": list(self.addr),
        }

    async def rpc_kill_worker(self, conn, payload) -> dict:
        resources = self.workers.pop(payload.get("worker_id") or "", None)
        if resources is not None:
            self._restore(resources)
        return {"status": "ok"}

    async def rpc_prepare_bundle(self, conn, payload) -> dict:
        key = (payload["pg_id"], payload["bundle_index"])
        resources = payload["resources"]
        if key in self.bundles:
            return {"status": "ok"}
        if not self._fits(resources):
            return {"status": "busy"}
        self._consume(resources)
        self.bundles[key] = dict(resources)
        return {"status": "ok"}

    async def rpc_commit_bundle(self, conn, payload) -> dict:
        return {"status": "ok"}

    async def rpc_release_bundle(self, conn, payload) -> dict:
        key = (payload["pg_id"], payload["bundle_index"])
        resources = self.bundles.pop(key, None)
        if resources is not None:
            self._restore(resources)
        return {"status": "ok"}

    async def rpc_ping(self, conn, payload) -> dict:
        return {"status": "ok"}

    # -- lifecycle --------------------------------------------------------
    def _stats(self) -> dict:
        return {
            "workers": len(self.workers),
            "idle_workers": 0,
            "leases": len(self.workers),
            "bundles": len(self.bundles),
            "resource_waiters": 0,
        }

    def _telemetry_sample(self) -> dict:
        """Honest telemetry for a fake node: real psutil CPU + this
        process's memory stand in for the node (all fakes share the
        process), synthetic per-worker RSS for the fake workers. Keeps
        the telemetry acceptance path (2-node FakeScaleCluster →
        summarize_resources) exercising real sampling code."""
        import time as _time

        sample: dict = {"ts": _time.time(), "num_workers": len(self.workers)}
        try:
            import psutil

            vmem = psutil.virtual_memory()
            sample["cpu_percent"] = psutil.cpu_percent(None)
            sample["mem_used"] = int(vmem.total - vmem.available)
            sample["mem_total"] = int(vmem.total)
            rss = int(psutil.Process().memory_info().rss)
        except Exception:
            rss = 0
        worker_rss = {wid: rss for wid in self.workers}
        sample["worker_rss"] = worker_rss
        sample["workers_rss_total"] = sum(worker_rss.values())
        sample["workers_rss_max"] = max(worker_rss.values(), default=0)
        sample["object_store_bytes"] = 0
        return sample

    async def heartbeat(self) -> dict:
        self.heartbeats_sent += 1
        return await self.client.call(
            "heartbeat",
            {
                "node_id": self.node_id,
                "resources_available": dict(self.available),
                "stats": self._stats(),
                "telemetry": [self._telemetry_sample()],
            },
        )

    async def start(self, heartbeat_period_s: float = 1.0) -> None:
        import asyncio

        self.server.route_object(self)
        port = await self.server.start("127.0.0.1", 0)
        self.addr = ("127.0.0.1", port)
        await self.client.connect()
        await self.client.call(
            "register_node",
            {
                "node_id": self.node_id,
                "agent_addr": list(self.addr),
                "resources": self.resources_total,
                "store_info": {},
                "labels": {"fake": "1"},
                "live_actors": [],
                "held_bundles": [],
            },
        )
        if heartbeat_period_s > 0:
            self._hb_task = asyncio.ensure_future(
                self._heartbeat_loop(heartbeat_period_s)
            )

    async def _heartbeat_loop(self, period: float) -> None:
        import asyncio

        while True:
            await asyncio.sleep(period)
            try:
                await self.heartbeat()
            except Exception:
                await asyncio.sleep(1.0)

    async def stop(self) -> None:
        if self._hb_task is not None:
            self._hb_task.cancel()
            self._hb_task = None
        try:
            await self.client.close()
        except Exception:  # rtlint: disable=swallowed-exception - client close at shutdown
            pass
        try:
            await self.server.stop()
        except Exception:  # rtlint: disable=swallowed-exception - server stop at shutdown
            pass


class FakeScaleCluster:
    """In-process control-plane scale rig: real Controller + N FakeNodeAgents
    on the current event loop, plus a driver RPC client. Used by
    release/benchmarks_scale.py and ci/run_scale_smoke.sh."""

    def __init__(self, num_nodes: int, cpus_per_node: float = 64.0,
                 heartbeat_period_s: float = 1.0,
                 session_dir: str | None = None):
        self.num_nodes = num_nodes
        self.cpus_per_node = float(cpus_per_node)
        self.heartbeat_period_s = heartbeat_period_s
        self._session_dir = session_dir
        self._tmpdir = None
        self.controller = None
        self.controller_addr: tuple | None = None
        self.agents: list[FakeNodeAgent] = []
        self.driver = None

    async def start(self) -> None:
        import tempfile

        from ray_tpu._private.controller import Controller
        from ray_tpu._private.rpc import RpcClient

        if self._session_dir is None:
            self._tmpdir = tempfile.TemporaryDirectory(prefix="raytpu-scale-")
            self._session_dir = self._tmpdir.name
        self.controller = Controller(self._session_dir)
        port = await self.controller.start("127.0.0.1", 0)
        self.controller_addr = ("127.0.0.1", port)
        for i in range(self.num_nodes):
            agent = FakeNodeAgent(
                i, self.controller_addr, {"CPU": self.cpus_per_node}
            )
            await agent.start(self.heartbeat_period_s)
            self.agents.append(agent)
        self.driver = RpcClient(self.controller_addr, name="scale-driver")
        await self.driver.connect()
        await self.driver.call(
            "register_client",
            {"worker_id": "drv-scale", "is_driver": False,
             "job_id": "scale-bench"},
        )

    async def add_node(self) -> FakeNodeAgent:
        agent = FakeNodeAgent(
            len(self.agents), self.controller_addr,
            {"CPU": self.cpus_per_node},
        )
        await agent.start(self.heartbeat_period_s)
        self.agents.append(agent)
        return agent

    async def controller_stats(self) -> dict:
        return await self.driver.call("controller_stats", {})

    async def stop(self) -> None:
        if self.driver is not None:
            try:
                await self.driver.close()
            except Exception:  # rtlint: disable=swallowed-exception - driver conn close at teardown
                pass
        for agent in self.agents:
            await agent.stop()
        self.agents.clear()
        if self.controller is not None:
            try:
                await self.controller.server.stop()
            except Exception:  # rtlint: disable=swallowed-exception - controller already stopped
                pass
        try:
            import asyncio

            from ray_tpu._private.rpc import _NativeEngine

            _NativeEngine.destroy_for_loop(asyncio.get_running_loop())
        except Exception:  # rtlint: disable=swallowed-exception - no running loop or engine already destroyed
            pass
        if self._tmpdir is not None:
            self._tmpdir.cleanup()

"""Workload flight recorder — the math (ISSUE 8).

Where ``_private/telemetry.py`` answers "what is the cluster eating",
this module answers "what is the *workload* doing with it": per-step
training phase breakdown (data-wait / compute / collective / checkpoint),
rolling tokens/s and MFU, MAD-based straggler detection, goodput bucket
accounting for elastic runs, and the fixed-bucket latency histogram the
serve path uses for per-route p50/p95/p99.

Everything here is pure, dependency-free math so it is unit-testable
without a cluster and safe to run on the controller's asyncio thread.
Chaos safety mirrors the telemetry store's monotonic guard: the
heartbeat/RPC layer can duplicate, drop, or replay batches, so the
aggregator drops any record whose per-rank step index is not strictly
newer than the last one seen, and clamps negative phase durations to
zero — a replayed round can never double-count a step or push a phase
total backwards.
"""

from __future__ import annotations

import collections
from typing import Any, Iterable

# Per-rank phase fields of a StepStats record (seconds). ``wall_s`` is
# the full report-to-report interval; ``compute_s`` is derived as the
# remainder so the phases always sum to wall. ``pp_bubble_s`` is time a
# pipeline stage spent blocked on a neighbor's activations (ISSUE 10) —
# zero on non-pipelined runs. ``comm_exposed_s`` (ISSUE 11) is the slice
# of collective time the step actually BLOCKED on under overlapped
# gradient sync; when the overlap path ran, the compute remainder
# subtracts the exposed slice instead of ``collective_s`` (the total op
# time, which keeps accumulating on background threads), so wall is
# partitioned by what stole step time, not by where work happened.
STEP_PHASES = (
    "data_wait_s",
    "compute_s",
    "collective_s",
    "checkpoint_s",
    "pp_bubble_s",
    "comm_exposed_s",
)

# Sub-phase split of ``compute_s`` (ISSUE 20): ranks running with step
# annotations report how compute divides into forward, backward, and
# optimizer time. These are *additive detail* under compute_s — they
# never enter the wall-partition identity above, and ranks that cannot
# split (fused GSPMD single-program path) simply omit them.
SUB_PHASES = (
    "fwd_s",
    "bwd_s",
    "opt_s",
)

# Peak bf16 FLOP/s per chip kind — must match release/bench_mfu.py
# (bench.py), which is the acceptance reference: in-framework MFU and
# the out-of-band benchmark must agree within 2% on the same run.
PEAK_FLOPS_BY_KIND = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}


def peak_flops_per_chip(device_kind: str | None) -> float | None:
    """bench.py's peaks table, matched by prefix. None for unknown kinds
    (CPU test runs): MFU is then simply not reported rather than wrong."""
    if not device_kind:
        return None
    return next(
        (v for k, v in PEAK_FLOPS_BY_KIND.items() if device_kind.startswith(k)),
        None,
    )


def flops_for_tokens(params: int, tokens: float) -> float:
    """The fwd+bwd rule of thumb bench.py uses: 6 * params * tokens."""
    return 6.0 * float(params) * float(tokens)


def _num(value: Any, default: float = 0.0) -> float:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return default
    return float(value)


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


class StepStatsAggregator:
    """Folds per-rank StepStats records into gang-level rolling stats.

    Lives on the train driver (one per fit()) and feeds both the
    controller workload series and the straggler detector. ``window``
    bounds every internal structure — a week-long run costs the same
    memory as a minute-long one.
    """

    def __init__(self, window: int = 64):
        self.window = max(4, int(window))
        # Chaos guard: last step index ingested per rank. Replayed or
        # duplicated rounds re-deliver old step indices and are dropped.
        self._last_step: dict[int, int] = {}
        # step -> {"walls": {rank: wall_s}, "ts": float, "tokens": float,
        #          "flops": float, phase sums...}; bounded to `window`.
        self._by_step: collections.OrderedDict[int, dict] = (
            collections.OrderedDict()
        )
        self._rank_node: dict[int, str] = {}
        self._rank_peak: dict[int, float] = {}
        self.steps_ingested = 0
        self.records_ingested = 0
        self.dropped_stale = 0  # dup/replayed records (chaos)
        self.clamped_negative = 0  # negative phase durations clamped to 0

    def add(self, rec: dict) -> bool:
        if not isinstance(rec, dict):
            return False
        step = rec.get("step")
        if isinstance(step, bool) or not isinstance(step, (int, float)):
            self.dropped_stale += 1
            return False
        step = int(step)
        rank = int(_num(rec.get("rank"), -1))
        if step <= self._last_step.get(rank, -1):
            self.dropped_stale += 1
            return False
        self._last_step[rank] = step

        wall = _num(rec.get("wall_s"))
        if wall < 0:
            self.clamped_negative += 1
            wall = 0.0
        phases: dict[str, float] = {}
        for phase in STEP_PHASES + SUB_PHASES:
            v = _num(rec.get(phase))
            if v < 0:
                self.clamped_negative += 1
                v = 0.0
            phases[phase] = v

        node_id = rec.get("node_id")
        if isinstance(node_id, str) and node_id:
            self._rank_node[rank] = node_id
        peak = peak_flops_per_chip(rec.get("device_kind"))
        if peak:
            self._rank_peak[rank] = peak * max(1, int(_num(rec.get("devices"), 1)))

        entry = self._by_step.get(step)
        if entry is None:
            entry = self._by_step[step] = {
                "walls": {},
                "ts": 0.0,
                "tokens": 0.0,
                "flops": 0.0,
                **{p: 0.0 for p in STEP_PHASES + SUB_PHASES},
            }
            self.steps_ingested += 1
            while len(self._by_step) > self.window:
                self._by_step.popitem(last=False)
        entry["walls"][rank] = wall
        entry["ts"] = max(entry["ts"], _num(rec.get("ts")))
        entry["tokens"] += _num(rec.get("tokens"))
        entry["flops"] += _num(rec.get("flops"))
        for phase in STEP_PHASES + SUB_PHASES:
            entry[phase] += phases[phase]
        self.records_ingested += 1
        return True

    # -- rolling throughput / breakdown ---------------------------------
    def summary(self) -> dict:
        """Gang-level rolling stats over the window: tokens/s, MFU (when
        the chip kind is known), and the phase breakdown as fractions of
        total per-rank step time."""
        steps = list(self._by_step.values())
        gang_wall = sum(
            max(e["walls"].values()) for e in steps if e["walls"]
        )
        tokens = sum(e["tokens"] for e in steps)
        flops = sum(e["flops"] for e in steps)
        rank_wall_total = sum(sum(e["walls"].values()) for e in steps)
        phase_fracs = {}
        for phase in STEP_PHASES:
            total = sum(e[phase] for e in steps)
            phase_fracs[phase.replace("_s", "_frac")] = (
                total / rank_wall_total if rank_wall_total > 0 else 0.0
            )
        # Sub-phase fracs (compute split) only when at least one rank
        # reported a split — an all-zero "fwd_frac: 0.0" would read as
        # "forward is free" rather than "no data".
        for phase in SUB_PHASES:
            total = sum(e.get(phase, 0.0) for e in steps)
            if total > 0 and rank_wall_total > 0:
                phase_fracs[phase.replace("_s", "_frac")] = (
                    total / rank_wall_total
                )
        peak_total = sum(self._rank_peak.values()) or None
        mfu = None
        if peak_total and gang_wall > 0:
            mfu = (flops / gang_wall) / peak_total
        return {
            "steps": self.steps_ingested,
            "window_steps": len(steps),
            "world_size": len(self._last_step),
            "tokens_per_s": tokens / gang_wall if gang_wall > 0 else 0.0,
            "flops_per_s": flops / gang_wall if gang_wall > 0 else 0.0,
            "mfu": mfu,
            **phase_fracs,
            "records": self.records_ingested,
            "dropped_stale": self.dropped_stale,
            "clamped_negative": self.clamped_negative,
        }

    # -- straggler detection --------------------------------------------
    def straggler_report(
        self,
        k: float = 3.0,
        min_steps: int = 8,
        min_fraction: float = 0.5,
    ) -> list[dict]:
        """Ranks persistently slower than the gang.

        Per step, a rank is flagged when its wall time exceeds
        ``median + k * MAD`` across the gang (MAD floored at 2% of the
        median so a perfectly uniform gang with float jitter never
        flags). A rank is a *straggler* when it was flagged in at least
        ``min_fraction`` of the last ``min_steps``-or-more multi-rank
        steps — one slow step is noise; a persistent offset is a sick
        host."""
        flagged: dict[int, int] = {}
        excess: dict[int, list[float]] = {}
        considered = 0
        for entry in self._by_step.values():
            walls = entry["walls"]
            if len(walls) < 2:
                continue
            considered += 1
            vals = list(walls.values())
            med = _median(vals)
            mad = _median([abs(v - med) for v in vals])
            floor = max(mad, 0.02 * med, 1e-6)
            threshold = med + k * floor
            for rank, wall in walls.items():
                if wall > threshold:
                    flagged[rank] = flagged.get(rank, 0) + 1
                    if med > 0:
                        excess.setdefault(rank, []).append(wall / med)
        if considered < min_steps:
            return []
        out = []
        for rank, count in sorted(flagged.items()):
            if count / considered >= min_fraction:
                ratios = excess.get(rank) or [1.0]
                out.append(
                    {
                        "rank": rank,
                        "node_id": self._rank_node.get(rank, ""),
                        "flagged_steps": count,
                        "window_steps": considered,
                        "excess_ratio": sum(ratios) / len(ratios),
                    }
                )
        return out


def goodput_buckets(
    wall_s: float,
    checkpoint_s: float = 0.0,
    restart_s: float = 0.0,
    stalled_s: float = 0.0,
) -> dict:
    """Classify an elastic run's wall clock (ISSUE 8 tentpole b).

    productive = wall − checkpoint − restart − stalled, so the four
    buckets sum to wall *by construction* (the acceptance criterion asks
    for ≤1% error; this gives 0). Bucket definitions:

      checkpoint : driver-side commit (StorageContext.persist) plus the
                   slowest rank's in-step save time per round
      restart    : gang (re)formation, executor start, and restart
                   backoff sleeps — the resize/re-form tax
      stalled    : wall time between the last productive round and
                   failure detection — lost (uncommitted) work
      productive : everything else, i.e. training steps that committed
    """
    wall = max(0.0, float(wall_s))
    ckpt = min(wall, max(0.0, float(checkpoint_s)))
    restart = min(wall - ckpt, max(0.0, float(restart_s)))
    stalled = min(wall - ckpt - restart, max(0.0, float(stalled_s)))
    productive = wall - ckpt - restart - stalled
    return {
        "wall_s": wall,
        "productive_s": productive,
        "checkpoint_s": ckpt,
        "restart_s": restart,
        "stalled_s": stalled,
        "goodput_fraction": productive / wall if wall > 0 else 0.0,
    }


class LatencyHistogram:
    """Fixed log-spaced latency histogram with nearest-bucket percentiles.

    O(1) observe, O(buckets) percentile, bounded memory — the serve
    proxy keeps one per route and replicas one per process, so this must
    never grow with traffic the way the old unbounded latency list did.
    Bounds span 0.1 ms .. 60 s (HTTP inference latencies).
    """

    _BOUNDS: tuple[float, ...] = tuple(
        0.0001 * (1.7 ** i) for i in range(26)
    )  # 0.1ms .. ~54s, ratio 1.7 → ≤35% bucket error at p99

    def __init__(self):
        self.counts = [0] * (len(self._BOUNDS) + 1)
        self.count = 0
        self.sum_s = 0.0
        self.max_s = 0.0

    def observe(self, seconds: float) -> None:
        s = max(0.0, float(seconds))
        self.count += 1
        self.sum_s += s
        if s > self.max_s:
            self.max_s = s
        for i, bound in enumerate(self._BOUNDS):
            if s <= bound:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def percentile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile (seconds)."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        cum = 0
        for i, n in enumerate(self.counts):
            cum += n
            if cum >= target and n:
                return (
                    self._BOUNDS[i] if i < len(self._BOUNDS) else self.max_s
                )
        return self.max_s

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean_ms": 1e3 * self.sum_s / self.count if self.count else 0.0,
            "p50_ms": 1e3 * self.percentile(0.50),
            "p95_ms": 1e3 * self.percentile(0.95),
            "p99_ms": 1e3 * self.percentile(0.99),
            "max_ms": 1e3 * self.max_s,
        }


# ---------------------------------------------------------------------------
# Diagnose — ranked findings over a snapshot of every observability
# surface (`ray_tpu diagnose`). Pure function of the snapshot dict so the
# rule set is unit-testable without a cluster.
# ---------------------------------------------------------------------------

# Fractions of step time above which a phase dominates the verdict.
DATA_BOUND_FRAC = 0.25
COMM_BOUND_FRAC = 0.30
CKPT_BOUND_FRAC = 0.10
GOODPUT_WARN_FRACTION = 0.90
SERVE_P99_SLO_MS = 250.0
CPU_SATURATED_PCT = 90.0
# Token-level serving SLOs (ISSUE 19): TTFT is request-latency-shaped
# (queue + prefill + KV transfer + first decode step); TPOT is one
# decode iteration.
SERVE_TTFT_SLO_MS = 500.0
SERVE_TPOT_SLO_MS = 100.0
# KV-headroom exhaustion trend: projection horizon and the free-frac
# floor under which the projection counts as exhaustion (same shape as
# the node agent's oom_risk projection).
KV_TREND_HORIZON_S = 60.0
KV_EXHAUSTION_FRAC = 0.05


def _finding(severity: str, score: float, kind: str, message: str,
             data: dict | None = None) -> dict:
    return {
        "severity": severity,
        "score": float(score),
        "kind": kind,
        "message": message,
        "data": data or {},
    }


def _latest_train_summaries(workload: dict) -> dict[str, dict]:
    """{experiment: latest gang-summary sample} from the workload series."""
    out = {}
    for key, entry in (workload.get("series") or {}).items():
        if key.startswith("train/") and "/" not in key[len("train/"):]:
            latest = entry.get("latest")
            if isinstance(latest, dict):
                out[key[len("train/"):]] = latest
    return out


def diagnose(snapshot: dict) -> list[dict]:
    """Rank what is wrong (or notable) about the workload.

    ``snapshot`` is the blob ``util.state.collect_diagnose_snapshot()``
    assembles: {"latency", "comm", "resources", "goodput", "workload",
    "rank_records": {experiment: [StepStats...]}}. Returns findings
    sorted most-severe first; each has severity/score/kind/message/data.
    """
    findings: list[dict] = []
    workload = snapshot.get("workload") or {}
    resources = snapshot.get("resources") or {}
    nodes = resources.get("nodes") or {}

    # -- comm-plane stalls (ISSUE 14) ----------------------------------
    # A suspected wedge outranks every throughput finding: nothing else
    # in the snapshot matters while a collective is stuck.
    commflight = snapshot.get("commflight") or {}
    stall_total = int(commflight.get("stall_total") or 0)
    if stall_total:
        recent = commflight.get("stalls") or []
        last = recent[-1] if recent else {}
        chans = sorted({
            e.get("channel") for e in recent[-8:] if e.get("channel")
        })
        findings.append(_finding(
            "crit", 200 + 10 * stall_total, "comm_stall",
            f"comm watchdog suspects {stall_total} stalled comm op(s) "
            f"on {', '.join(chans) or 'unknown channels'} — run "
            "`ray_tpu doctor --hang` for the rank-level hang report",
            {
                "stall_total": stall_total,
                "channels": chans,
                "last_stall": last,
                "hang_reports": commflight.get("hang_reports", 0),
            },
        ))

    # -- training phase balance ----------------------------------------
    train = _latest_train_summaries(workload)
    for exp, s in train.items():
        data_frac = _num(s.get("data_wait_frac"))
        comm_frac = _num(s.get("collective_frac"))
        ckpt_frac = _num(s.get("checkpoint_frac"))
        tps = _num(s.get("tokens_per_s"))
        mfu = s.get("mfu")
        if data_frac >= DATA_BOUND_FRAC:
            findings.append(_finding(
                "warn", 50 + 100 * data_frac, "data_bound",
                f"{exp}: data-bound — {data_frac:.0%} of step time in "
                f"data-wait (tokens/s {tps:,.0f}); add ingest "
                "parallelism or prefetch",
                {"experiment": exp, "data_wait_frac": data_frac},
            ))
        if comm_frac >= COMM_BOUND_FRAC:
            findings.append(_finding(
                "warn", 45 + 100 * comm_frac, "comm_bound",
                f"{exp}: comm-bound — {comm_frac:.0%} of step time in "
                "collectives; consider quantized or hierarchical "
                "allreduce (docs/collectives.md)",
                {"experiment": exp, "collective_frac": comm_frac},
            ))
        if ckpt_frac >= CKPT_BOUND_FRAC:
            findings.append(_finding(
                "info", 20 + 100 * ckpt_frac, "checkpoint_heavy",
                f"{exp}: {ckpt_frac:.0%} of step time saving checkpoints"
                " — lower the checkpoint frequency or shard the save",
                {"experiment": exp, "checkpoint_frac": ckpt_frac},
            ))
        if isinstance(mfu, (int, float)) and mfu:
            findings.append(_finding(
                "info", 10 + 10 * float(mfu), "throughput",
                f"{exp}: MFU {float(mfu):.1%}, {tps:,.0f} tokens/s",
                {"experiment": exp, "mfu": float(mfu),
                 "tokens_per_s": tps},
            ))

    # -- stragglers (cross-referenced against node telemetry) -----------
    for exp, records in (snapshot.get("rank_records") or {}).items():
        agg = StepStatsAggregator()
        for rec in records or []:
            agg.add(rec)
        for s in agg.straggler_report():
            node_id = s.get("node_id") or ""
            latest = (nodes.get(node_id) or {}).get("latest") or {}
            cause = ""
            cpu = _num(latest.get("cpu_percent"))
            if cpu >= CPU_SATURATED_PCT:
                cause = f"; node {node_id[-8:] or '?'} CPU saturated ({cpu:.0f}%)"
            elif latest.get("mem_total") and _num(latest.get("mem_used")) \
                    / _num(latest.get("mem_total"), 1.0) >= 0.9:
                cause = f"; node {node_id[-8:] or '?'} memory pressure"
            elif node_id:
                cause = f"; on node {node_id[-8:]} (telemetry unremarkable)"
            findings.append(_finding(
                "crit", 80 + 10 * s["excess_ratio"], "straggler",
                f"{exp}: rank {s['rank']} straggling — "
                f"{s['excess_ratio']:.1f}x the gang median in "
                f"{s['flagged_steps']}/{s['window_steps']} recent steps"
                + cause,
                {"experiment": exp, **s, "node_latest": latest},
            ))

    # -- straggler hot phase (ISSUE 20 auto-profiling) ------------------
    # When an auto-capture ran against flagged rank(s), name the phase
    # that dominated the slow rank's step — the difference between "rank
    # 3 is slow" and "rank 3 spends 62% of its step blocked in
    # collectives; look at its NIC".
    auto_profile = next(
        (
            rec for rec in reversed(snapshot.get("profiles") or [])
            if isinstance(rec, dict)
            and rec.get("reason") != "manual"
            and rec.get("hot_phases")
        ),
        None,
    )
    if auto_profile is not None:
        for rank_key, hot in sorted(
            (auto_profile.get("hot_phases") or {}).items(),
            key=lambda kv: str(kv[0]),
        ):
            if not isinstance(hot, dict) or not hot.get("phase"):
                continue
            frac = _num(hot.get("frac"))
            findings.append(_finding(
                "crit", 120 + 100 * frac, "straggler_hot_phase",
                f"rank {rank_key}: auto-profile "
                f"{auto_profile.get('capture_id', '?')} "
                f"({auto_profile.get('reason', '?')}) attributes "
                f"{frac:.0%} of attributed step time to "
                f"'{hot['phase']}' — merged trace at "
                f"{auto_profile.get('path') or '<unavailable>'}",
                {
                    "rank": rank_key,
                    "phase": hot["phase"],
                    "frac": frac,
                    "capture_id": auto_profile.get("capture_id"),
                    "reason": auto_profile.get("reason"),
                    "path": auto_profile.get("path"),
                },
            ))

    # -- goodput --------------------------------------------------------
    for exp, g in ((snapshot.get("goodput") or {}).get("runs") or {}).items():
        frac = _num(g.get("goodput_fraction"))
        wall = _num(g.get("wall_s"))
        if wall <= 0:
            continue
        if frac < GOODPUT_WARN_FRACTION:
            losses = sorted(
                (
                    (bucket, _num(g.get(bucket)) / wall)
                    for bucket in ("restart_s", "stalled_s", "checkpoint_s")
                ),
                key=lambda kv: -kv[1],
            )
            top, top_frac = losses[0]
            findings.append(_finding(
                "warn", 40 + 100 * (1 - frac), "goodput",
                f"{exp}: goodput {frac:.0%} — {top_frac:.0%} of wall "
                f"clock lost to {top.replace('_s', '')}",
                {"experiment": exp, **g},
            ))
        else:
            findings.append(_finding(
                "info", 5 + 10 * frac, "goodput",
                f"{exp}: goodput {frac:.0%} over {wall:.0f}s wall clock",
                {"experiment": exp, **g},
            ))

    # -- serve SLO ------------------------------------------------------
    for key, entry in (workload.get("series") or {}).items():
        if not key.startswith("serve/"):
            continue
        latest = entry.get("latest") or {}
        route = key[len("serve/"):]
        p99 = _num(latest.get("p99_ms"))
        errors = _num(latest.get("errors"))
        if p99 >= SERVE_P99_SLO_MS:
            findings.append(_finding(
                "warn", 40 + p99 / 10.0, "serve_slo",
                f"serve {route}: p99 {p99:.0f}ms over the "
                f"{SERVE_P99_SLO_MS:.0f}ms SLO "
                f"(p50 {_num(latest.get('p50_ms')):.0f}ms, "
                f"{_num(latest.get('qps')):.1f} qps)",
                {"route": route, **latest},
            ))
        if errors:
            findings.append(_finding(
                "warn", 35 + errors, "serve_errors",
                f"serve {route}: {errors:.0f} failed requests",
                {"route": route, **latest},
            ))

    # -- token-level serving SLOs (ISSUE 19) ----------------------------
    serve_llm = snapshot.get("serve_llm") or {}
    seq_count = int(serve_llm.get("count") or 0)
    if seq_count:
        ttft_p99_ms = 1e3 * _num(serve_llm.get("ttft_p99_s"))
        tpot_p99_ms = 1e3 * _num(serve_llm.get("tpot_p99_s"))
        if ttft_p99_ms >= SERVE_TTFT_SLO_MS:
            findings.append(_finding(
                "warn", 42 + ttft_p99_ms / 10.0, "serve_ttft_slo",
                f"serve llm: TTFT p99 {ttft_p99_ms:.0f}ms over the "
                f"{SERVE_TTFT_SLO_MS:.0f}ms SLO across {seq_count} "
                "sequence(s) — check queue wait vs prefill in "
                "`ray_tpu timeline --seq <id>`",
                {"ttft_p99_ms": ttft_p99_ms, "sequences": seq_count,
                 "by_outcome": serve_llm.get("by_outcome", {})},
            ))
        if tpot_p99_ms >= SERVE_TPOT_SLO_MS:
            findings.append(_finding(
                "warn", 41 + tpot_p99_ms / 10.0, "serve_tpot_slo",
                f"serve llm: inter-token p99 {tpot_p99_ms:.0f}ms over "
                f"the {SERVE_TPOT_SLO_MS:.0f}ms SLO — the decode step "
                "is slow or the batch is oversubscribed",
                {"tpot_p99_ms": tpot_p99_ms, "sequences": seq_count},
            ))
        ledger = serve_llm.get("ledger") or {}
        issued = int(ledger.get("issued") or 0)
        wasted = (
            int(ledger.get("evicted") or 0)
            + int(ledger.get("replay_discarded") or 0)
        )
        if issued and wasted / issued >= 0.10:
            findings.append(_finding(
                "warn", 38 + 100.0 * wasted / issued, "token_goodput",
                f"serve llm: {wasted / issued:.0%} of {issued} issued "
                "token(s) were wasted (evicted or replay-discarded) — "
                "decode work that never reached a client",
                {"ledger": ledger},
            ))
    # KV-headroom exhaustion trend: least-squares over the (ts,
    # free_frac) history the decode engines export, projected
    # KV_TREND_HORIZON_S forward — the paged-pool analogue of the node
    # agent's oom_risk warner (telemetry.project_rss does the fit).
    kv_history = serve_llm.get("kv_history") or []
    if len(kv_history) >= 3:
        from ray_tpu._private.telemetry import project_rss

        projected = project_rss(kv_history, KV_TREND_HORIZON_S)
        current = _num(kv_history[-1][1])
        if (
            projected is not None
            and projected <= KV_EXHAUSTION_FRAC < current
        ):
            findings.append(_finding(
                "warn", 55 + 100 * (current - projected),
                "kv_headroom_trend",
                f"serve llm: KV free fraction {current:.0%} trending to "
                f"{max(projected, 0.0):.0%} within "
                f"{KV_TREND_HORIZON_S:.0f}s — the paged pool is heading "
                "for exhaustion (scale decode or shed earlier)",
                {"kv_free_frac": current,
                 "projected_free_frac": projected,
                 "horizon_s": KV_TREND_HORIZON_S,
                 "points": len(kv_history)},
            ))

    # -- node-level hot spots (even without a training run) -------------
    for node_id, entry in nodes.items():
        latest = entry.get("latest") or {}
        cpu = _num(latest.get("cpu_percent"))
        if cpu >= CPU_SATURATED_PCT:
            findings.append(_finding(
                "info", 15 + cpu / 10, "node_cpu",
                f"node {node_id[-8:]}: CPU {cpu:.0f}% — saturated",
                {"node_id": node_id, "cpu_percent": cpu},
            ))
    oom_events = _num(resources.get("oom_risk_events"))
    if oom_events:
        findings.append(_finding(
            "warn", 60 + oom_events, "oom_risk",
            f"{oom_events:.0f} oom_risk event(s) — a worker is trending "
            "toward the memory kill limit (see events_oom_risk.jsonl)",
            {"oom_risk_events": oom_events},
        ))

    if not findings:
        findings.append(_finding(
            "info", 1, "no_data",
            "no workload records found — is a training job or serve app "
            "running with workload stats enabled "
            "(RAY_TPU_workload_stats_enabled)?",
        ))
    findings.sort(key=lambda f: -f["score"])
    return findings

"""Atomic small-file writes — the PR-6 tmp-then-rename idiom, shared.

Every small state/metadata file in the framework (checkpoint manifests,
snapshots, usage stats, experiment state, run records) must land
atomically: a crash mid-write may leave a stale file or a stray ``.tmp``,
but never a torn file at the final name. Readers either see the old
content or the new, complete content.

The tmp name carries the pid so concurrent writers (driver + train
workers sharing a session file) cannot clobber each other's in-flight
temp; the final ``os.replace`` is atomic within a filesystem.

``rtlint``'s ``non-atomic-write`` rule flags raw ``open(path, "w")``
writes in framework code — route them through these helpers instead.
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any


def atomic_write_bytes(path: str, data: bytes, *, fsync: bool = False) -> None:
    """Write ``data`` to ``path`` via tmp + ``os.replace``.

    ``fsync=True`` additionally flushes the file to stable storage before
    the rename — use for commit markers whose loss would violate a
    durability protocol (checkpoint COMMIT files), not for best-effort
    telemetry.
    """
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        # rtlint: disable=blocking-in-async - sync-by-design durability primitive (write+fsync+rename); async callers write small metadata blobs where atomicity beats a thread hop
        with open(tmp, "wb") as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # Never leave the temp behind on failure (ENOSPC, kill signal
        # unwinding): the torn content must not be mistaken for a
        # pending write by cleanup scanners.
        try:
            os.unlink(tmp)
        except OSError:  # rtlint: disable=swallowed-exception - tmp already renamed or never created
            pass
        raise


def atomic_write_text(path: str, text: str, *, fsync: bool = False) -> None:
    atomic_write_bytes(path, text.encode(), fsync=fsync)


def atomic_write_json(path: str, obj: Any, *, fsync: bool = False,
                      **dump_kwargs: Any) -> None:
    atomic_write_bytes(
        path, json.dumps(obj, **dump_kwargs).encode(), fsync=fsync
    )


def atomic_write_pickle(path: str, obj: Any, *, fsync: bool = False) -> None:
    atomic_write_bytes(path, pickle.dumps(obj), fsync=fsync)

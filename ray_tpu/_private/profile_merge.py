"""Merge per-rank profile captures into ONE Perfetto trace (ISSUE 20).

Pure functions over the payloads :meth:`ProfilePlane.collect` returns —
no I/O, no cluster state — so the merge is deterministic and unit-testable:
the same capture payloads always produce byte-identical JSON.

Output layout (Trace Event Format, loads in ui.perfetto.dev):

  * one pid (track group) per rank, named ``rank R (worker …)``,
  * tid 0 "steps": one "X" slice per captured step, args carrying the
    step index + the PR-4/PR-18 trace ids the boundary observed — the
    join key back to ``ray_tpu timeline``,
  * tid 1 "phases": the ``step_annotation()`` slices (fwd/bwd/opt,
    per-bucket fence waits), each stamped with the step whose window
    contains it,
  * metadata: capture id/reason, per-rank device-trace dirs (the raw
    ``jax.profiler`` XPlane output stays on the worker's node; this file
    points at it), host-sample counts, phase totals.

Folded host stacks merge separately (:func:`merge_folded`) into the
collapsed-stack format flamegraph tools eat, plus a hierarchical JSON
tree (:func:`flamegraph_tree`) for the dashboard.
"""

from __future__ import annotations


def _rank_key(cap: dict):
    rank = cap.get("rank")
    return (rank is None, rank if rank is not None else 0)


def _step_of(ts_us: float, step_windows: list[tuple[float, float, int]]) -> int | None:
    for start, end, step in step_windows:
        if start <= ts_us < end:
            return step
    return None


def merge_captures(
    captures: list[dict],
    capture_id: str,
    meta: dict | None = None,
) -> dict:
    """Per-rank capture payloads → one Chrome/Perfetto trace dict."""
    caps = sorted(
        (c for c in captures if isinstance(c, dict)), key=_rank_key
    )
    events: list[dict] = []
    trace_ids: set[str] = set()
    device_dirs: dict[str, str] = {}
    host_samples: dict[str, int] = {}
    phase_totals: dict[str, dict[str, float]] = {}
    for i, cap in enumerate(caps):
        rank = cap.get("rank")
        pid = rank if rank is not None else 9000 + i
        rank_label = f"rank {rank}" if rank is not None else f"worker[{i}]"
        wid = str(cap.get("worker_id") or "")
        label = f"{rank_label} ({wid[-12:]})" if wid else rank_label
        events.append(
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": label}}
        )
        events.append(
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": "steps"}}
        )
        events.append(
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
             "args": {"name": "phases"}}
        )
        # Step slices: boundaries are END-of-step marks; the slice for
        # step b[k+1].step spans b[k].ts → b[k+1].ts.
        bounds = [
            b for b in (cap.get("boundaries") or [])
            if isinstance(b, dict) and "ts" in b and "step" in b
        ]
        step_windows: list[tuple[float, float, int]] = []
        for prev, cur in zip(bounds, bounds[1:]):
            start_us = float(prev["ts"]) * 1e6
            end_us = float(cur["ts"]) * 1e6
            step = int(cur["step"])
            step_windows.append((start_us, end_us, step))
            args: dict = {"step": step, "capture_id": capture_id}
            if cur.get("trace_id"):
                args["trace_id"] = cur["trace_id"]
                trace_ids.add(str(cur["trace_id"]))
            if cur.get("span_id"):
                args["span_id"] = cur["span_id"]
            events.append(
                {
                    "name": f"step {step}",
                    "cat": "step",
                    "ph": "X",
                    "ts": start_us,
                    "dur": max(0.0, end_us - start_us),
                    "pid": pid,
                    "tid": 0,
                    "args": args,
                }
            )
        for prev in bounds:
            if prev.get("trace_id"):
                trace_ids.add(str(prev["trace_id"]))
        # Annotation slices (fwd/bwd/opt, fence buckets), sorted for
        # byte-stable output regardless of buffer interleaving.
        anns = sorted(
            (
                a for a in (cap.get("annotations") or [])
                if isinstance(a, dict) and "ts" in a
            ),
            key=lambda a: (float(a["ts"]), str(a.get("name", ""))),
        )
        for ann in anns:
            ts_us = float(ann["ts"]) * 1e6
            args = {"capture_id": capture_id}
            step = _step_of(ts_us, step_windows)
            if step is not None:
                args["step"] = step
            events.append(
                {
                    "name": str(ann.get("name", "annotation")),
                    "cat": "phase",
                    "ph": "X",
                    "ts": ts_us,
                    "dur": max(0.0, float(ann.get("dur_s") or 0.0) * 1e6),
                    "pid": pid,
                    "tid": 1,
                    "args": args,
                }
            )
        key = str(rank) if rank is not None else f"worker[{i}]"
        if cap.get("device_trace_dir"):
            device_dirs[key] = cap["device_trace_dir"]
        host = cap.get("host") or {}
        if host.get("samples"):
            host_samples[key] = int(host["samples"])
        if cap.get("phase_totals"):
            phase_totals[key] = {
                k: float(v)
                for k, v in sorted(cap["phase_totals"].items())
            }
    metadata = {
        "capture_id": capture_id,
        "ranks": sorted(
            c.get("rank") for c in caps if c.get("rank") is not None
        ),
        "trace_ids": sorted(trace_ids),
        "device_trace_dirs": device_dirs,
        "host_samples": host_samples,
        "phase_totals": phase_totals,
    }
    if meta:
        metadata.update(meta)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": metadata,
    }


# -- folded host stacks ---------------------------------------------------
def merge_folded(captures: list[dict]) -> dict[str, int]:
    """Sum per-rank folded stacks, prefixing each with its rank so the
    flamegraph keeps ranks separable. Deterministic: sorted keys."""
    merged: dict[str, int] = {}
    for cap in sorted(
        (c for c in captures if isinstance(c, dict)), key=_rank_key
    ):
        host = cap.get("host") or {}
        rank = cap.get("rank")
        prefix = f"rank{rank}" if rank is not None else "worker"
        for stack, count in (host.get("folded") or {}).items():
            key = f"{prefix};{stack}"
            merged[key] = merged.get(key, 0) + int(count)
    return dict(sorted(merged.items()))


def folded_text(folded: dict[str, int]) -> str:
    """Collapsed-stack text (``stack count`` per line) — the format
    flamegraph.pl / speedscope / inferno consume directly."""
    return "".join(
        f"{stack} {count}\n" for stack, count in sorted(folded.items())
    )


def flamegraph_tree(folded: dict[str, int]) -> dict:
    """Hierarchical {name, value, children} tree for the dashboard's
    flamegraph JSON route. Children sorted by name: deterministic."""
    root: dict = {"name": "all", "value": 0, "children": {}}
    for stack, count in folded.items():
        root["value"] += count
        node = root
        for frame in stack.split(";"):
            child = node["children"].get(frame)
            if child is None:
                child = {"name": frame, "value": 0, "children": {}}
                node["children"][frame] = child
            child["value"] += count
            node = child

    def _freeze(node: dict) -> dict:
        out = {"name": node["name"], "value": node["value"]}
        kids = [
            _freeze(c)
            for _, c in sorted(node["children"].items())
        ]
        if kids:
            out["children"] = kids
        return out

    return _freeze(root)


# -- hot-phase attribution ------------------------------------------------
def hot_phase(phase_totals: dict[str, float]) -> tuple[str | None, float]:
    """(hot phase name, fraction of attributed time) from one rank's
    captured phase totals. ``comm_exposed`` shadows ``collective`` when
    both fired (the overlap path records the total op time under
    collective AND the blocked slice under comm_exposed — only the
    exposed slice stole step time)."""
    totals = {
        k: float(v) for k, v in (phase_totals or {}).items() if v and v > 0
    }
    if "comm_exposed" in totals:
        totals.pop("collective", None)
    if not totals:
        return None, 0.0
    total = sum(totals.values())
    # Sort by (-value, name): deterministic winner on ties.
    phase, value = sorted(totals.items(), key=lambda kv: (-kv[1], kv[0]))[0]
    return phase, value / total if total > 0 else 0.0

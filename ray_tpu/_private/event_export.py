"""Structured event export — lifecycle events as JSONL files.

Role-equivalent of the reference's event/export framework
(src/ray/util/event.cc :: RayEvent + export API protos, SURVEY §2.1 N28):
every control-plane lifecycle change (node added/removed, actor state,
placement-group state, job start/finish, task events) is appended as one
self-describing JSON line under ``<session_dir>/events/``, for external
platforms to tail — independent of the live pubsub channels, which only
reach connected subscribers.

Files rotate at ``event_export_max_bytes`` (one ``.1`` backup) so a
chatty cluster cannot grow them unboundedly.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

from ray_tpu._private.config import global_config

# pubsub channel → export file stem
_CHANNEL_FILES = {
    "node_added": "node",
    "node_removed": "node",
    "actor_state": "actor",
    "pg_state": "placement_group",
    "job_started": "job",
    "job_finished": "job",
    "task_events": "task",
    # Trend-aware OOM early warning (ISSUE 5): the memory monitor saw a
    # worker's RSS slope projecting past the kill limit.
    "oom_risk": "oom_risk",
    # Comm watchdog suspected a stalled collective/p2p channel (ISSUE 14);
    # the controller follows up with a cluster-wide evidence harvest.
    "comm_stall": "comm_stall",
    # Step-profiler capture records (ISSUE 20): one per completed
    # (or failed) coordinated capture — manual CLI, straggler-triggered,
    # or comm-stall-triggered.
    "profile": "profile",
}


class EventExporter:
    """emit() is called from the controller's asyncio loop on every
    lifecycle broadcast — it only enqueues; a daemon writer thread does
    the disk I/O so a slow session-dir filesystem can never stall
    control-plane RPCs."""

    def __init__(self, session_dir: str):
        self.dir = os.path.join(session_dir, "events")
        self.enabled = global_config().event_export_enabled
        self._lock = threading.Lock()
        self._seq = 0
        self._queue: list[tuple[str, dict]] = []
        self._wake = threading.Event()
        self._writing = False
        self._writer: threading.Thread | None = None
        if self.enabled:
            os.makedirs(self.dir, exist_ok=True)

    def emit(self, source: str, payload: Any) -> None:
        if not self.enabled:
            return
        stem = _CHANNEL_FILES.get(source)
        if stem is None:
            return
        with self._lock:
            self._seq += 1
            record = {
                "event_id": f"{os.getpid():x}-{self._seq:08x}",
                "source_type": source,
                "timestamp": time.time(),
                "severity": "INFO",
                "data": payload,
            }
            self._queue.append((stem, record))
            if self._writer is None:
                self._writer = threading.Thread(
                    target=self._writer_loop, daemon=True,
                    name="event-export-writer",
                )
                self._writer.start()
        self._wake.set()

    def flush(self, timeout: float = 5.0) -> None:
        """Drain the queue synchronously (tests / shutdown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and not self._writing:
                    return
            self._wake.set()
            time.sleep(0.01)

    def _writer_loop(self) -> None:
        while True:
            self._wake.wait(timeout=1.0)
            self._wake.clear()
            with self._lock:
                batch, self._queue = self._queue, []
                self._writing = bool(batch)
            if not batch:
                continue
            # One open + one rotation check per stem per wakeup (a batch may
            # overshoot the rotation cap by its own size — bounded, fine).
            by_stem: dict[str, list[dict]] = {}
            for stem, record in batch:
                by_stem.setdefault(stem, []).append(record)
            for stem, records in by_stem.items():
                path = os.path.join(self.dir, f"events_{stem}.jsonl")
                self._rotate_if_needed(path)
                try:
                    with open(path, "a") as fh:
                        for record in records:
                            fh.write(json.dumps(record, default=str) + "\n")
                except OSError:
                    pass
            with self._lock:
                self._writing = False

    def _rotate_if_needed(self, path: str) -> None:
        cap = global_config().event_export_max_bytes
        try:
            if os.path.getsize(path) >= cap:
                os.replace(path, path + ".1")
        except OSError:
            pass


def read_events(session_dir: str, source: str | None = None) -> list[dict]:
    """Read exported events (newest file last); tests + dashboard route."""
    out: list[dict] = []
    events_dir = os.path.join(session_dir, "events")
    if not os.path.isdir(events_dir):
        return out
    names = sorted(os.listdir(events_dir))
    # backups first so ordering is oldest → newest
    for name in [n for n in names if n.endswith(".1")] + [
        n for n in names if n.endswith(".jsonl")
    ]:
        try:
            with open(os.path.join(events_dir, name)) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    record = json.loads(line)
                    if source is None or record.get("source_type") == source:
                        out.append(record)
        except OSError:
            continue
    return out

"""CoreContext — the in-process runtime of every driver and worker.

Role-equivalent of the reference's C++ core worker
(src/ray/core_worker/core_worker.cc :: CoreWorker [N18]) plus its satellite
managers: task submission (transport/normal_task_submitter.cc,
actor_task_submitter.cc [N19]), reference counting (reference_count.cc [N21]),
task retries + lineage (task_manager.cc [N22]), object recovery
(object_recovery_manager.cc [N23]), in-process memory store
(memory_store.cc [N24]) and the plasma provider [N25].

Sync public API over an asyncio core running on the IoThread.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import contextlib
import os
import threading
import time
import traceback
import weakref
from typing import Any, Callable, Sequence

from ray_tpu import exceptions
from ray_tpu._private import serialization, wire_gen
from ray_tpu._private.config import global_config
from ray_tpu.util import tracing
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store import ObjectStoreClient, ObjectStoreFull
from ray_tpu._private.rpc import (
    ConnectionLost, ERR, IoThread, REP, RpcClient, RpcError, RpcServer,
    native_available, spawn_task,
)

PENDING, INLINE, SHM, FAILED = "pending", "inline", "shm", "failed"

# Sentinel: the direct-lane get() could not prove everything local and the
# caller must fall back to the asyncio path.
_DIRECT_MISS = object()

# Zero-copy reads: values whose out-of-band buffers exceed this stay views
# onto the arena (object pinned until the value is GC'd); smaller values are
# copied out and released immediately.
_ZERO_COPY_THRESHOLD = 1 << 20


class ObjectState:
    __slots__ = (
        "status", "data", "locations", "size", "error", "event", "record",
        "waited",
    )

    def __init__(self):
        self.status = PENDING
        self.data: bytes | None = None
        self.locations: list[dict] = []
        self.size = 0
        self.error: str | None = None
        self.event = asyncio.Event()
        # Direct-lane backlink: the PendingTask whose native reply settles
        # this state (None for put()s and asyncio-path tasks).
        self.record: "PendingTask | None" = None
        # True once a loop-side waiter parked on `event`; caller-thread
        # settles then notify the loop (asyncio.Event is not thread-safe
        # to set from outside, and an unconditional call_soon_threadsafe
        # per task would cost a loop wakeup per task).
        self.waited = False


class LeasedWorker:
    __slots__ = ("worker_id", "address", "client", "lease_id", "agent_addr", "resources_key")

    def __init__(self, worker_id, address, client, lease_id, agent_addr, resources_key):
        self.worker_id = worker_id
        self.address = address
        self.client = client
        self.lease_id = lease_id
        self.agent_addr = agent_addr
        self.resources_key = resources_key


class PendingTask:
    __slots__ = (
        "spec", "attempts", "return_ids", "arg_refs", "done",
        "direct", "native_handle", "direct_worker", "settle_lock",
        "done_event", "queue_key",
    )

    def __init__(self, spec, return_ids, arg_refs):
        self.spec = spec
        self.attempts = 0
        self.return_ids = return_ids
        self.arg_refs = arg_refs
        self.done = False
        self.queue_key = None  # precomputed dispatcher-queue key
        # Direct-lane fields (set by the native submitter): the in-flight
        # C++ call handle, the pool worker it rode, and settle coordination
        # (first settler consumes the handle; others wait on done_event,
        # which is a threading.Event — safe to set from any thread).
        self.direct = False
        self.native_handle: int | None = None
        self.direct_worker: "DirectWorker | None" = None
        self.settle_lock: threading.Lock | None = None
        self.done_event: threading.Event | None = None

    def make_direct(self) -> None:
        self.direct = True
        self.settle_lock = threading.Lock()
        self.done_event = threading.Event()


class DirectWorker:
    """A leased worker conn owned by the direct-call lane (the lease-reuse
    role of a dispatcher, minus the asyncio machinery)."""

    __slots__ = ("leased", "conn_id", "inflight", "last_used", "dead")

    def __init__(self, leased: "LeasedWorker", conn_id: int):
        self.leased = leased
        self.conn_id = conn_id
        self.inflight = 0
        self.last_used = time.monotonic()
        self.dead = False


def _resources_key(resources: dict, runtime_env_hash: str) -> str:
    return repr(sorted(resources.items())) + "|" + runtime_env_hash


class CoreContext:
    def __init__(
        self,
        *,
        job_id: str,
        node_id: str,
        controller_addr: tuple,
        agent_addr: tuple,
        store_info: dict,
        is_driver: bool,
        worker_id: str | None = None,
    ):
        self.job_id = JobID(job_id)
        self.node_id = NodeID(node_id)
        self.worker_id = WorkerID(worker_id) if worker_id else WorkerID.random()
        self.is_driver = is_driver
        self.io = IoThread()
        self.controller_addr = tuple(controller_addr)
        self.agent_addr = tuple(agent_addr)
        self.store_info = store_info  # {socket, shm_path, capacity, spill_dir}
        self._store: ObjectStoreClient | None = None
        self._store_lock = threading.Lock()

        # owner-side object state (memory store + object directory)
        self._objects: dict[str, ObjectState] = {}
        # distributed refcounting
        self._local_refs: dict[str, int] = {}
        self._submitted_refs: dict[str, int] = {}
        self._borrowers: dict[str, set[str]] = {}
        self._borrowed: dict[str, tuple] = {}  # obj_id -> owner_addr we registered with
        self._refs_lock = threading.Lock()
        # lineage: obj_id -> PendingTask of creating task (kept while refs live)
        self._lineage: dict[str, PendingTask] = {}
        self._task_counter = 0
        self._put_counter = 0
        self._counter_lock = threading.Lock()

        # cancellation (reference: CoreWorker::CancelTask [N18] +
        # task_manager.cc cancelled-task bookkeeping)
        self._cancelled_tasks: set[str] = set()
        self._running_tasks: dict[str, RpcClient] = {}  # task_id -> worker client
        self._task_records: dict[str, PendingTask] = {}

        # Direct-call lane (native C++ call table, [N19] direct calls):
        # caller threads submit/settle without touching the asyncio loop.
        self._engine = None  # _NativeEngine of the io loop (set on connect)
        self._fastlane = None  # _fastlane C extension (set on connect)
        self._actor_spec_parts: dict[tuple, tuple] = {}
        self._direct_lock = threading.Lock()
        self._direct_pool: dict[str, list[DirectWorker]] = {}
        self._direct_grows: dict[str, int] = {}
        self._direct_backoff: dict[str, float] = {}
        self._direct_reaper_started = False
        self._actor_pending_slow: dict[str, int] = {}
        self._actor_spec_templates: dict[tuple, dict] = {}
        # Unsettled direct calls (GIL-guarded int): >=2 means a burst is in
        # flight, so submits use the buffered send (engine-thread writev)
        # instead of paying an inline syscall + preemption per frame.
        self._direct_unsettled = 0

        # lease cache: resources_key -> list[LeasedWorker]
        self._idle_leases: dict[str, list[LeasedWorker]] = {}
        self._task_queues: dict[str, asyncio.Queue] = {}
        self._active_dispatchers: dict[str, int] = {}
        self._submit_buf: collections.deque = collections.deque()
        self._submit_lock = threading.Lock()
        self._submit_scheduled = False
        self._lease_capacity_hint: dict[str, int] = {}
        self._enqueue_counter = 0
        # direct clients: address -> RpcClient
        self._clients: dict[tuple, RpcClient] = {}
        self._client_dials: dict[tuple, asyncio.Task] = {}
        # actor bookkeeping
        self._actor_addr_cache: dict[str, tuple] = {}
        self._actor_seq: dict[str, int] = {}
        self._actor_seq_lock = threading.Lock()
        # Per-actor in-order send gates (io-loop state): actor push frames
        # must hit the wire in seq order even when earlier submissions are
        # still resolving the actor's address (reference
        # actor_task_submitter.cc sends in order, replies pipeline freely).
        self._actor_send_gate: dict[str, dict] = {}

        self.controller: RpcClient | None = None
        self._subscribed_channels: set[str] = set()
        self.agent: RpcClient | None = None
        self.core_server = RpcServer(name=f"core-{self.worker_id[:12]}")
        self.address: tuple | None = None

        # function table cache (worker side)
        self._function_cache: dict[str, Any] = {}
        # Slim lifecycle-event tuples buffered by the worker runtime:
        # (task_id, name, state, start_ts, ts, resources|None) — expanded
        # into full records at flush (worker_proc._record_task_event).
        self._task_events: list[tuple] = []
        self._shutdown = False

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def connect(self) -> None:
        self.io.run(self._connect_async())

    async def _connect_async(self) -> None:
        self.core_server.route_object(self)
        port = await self.core_server.start()
        self.address = ("127.0.0.1", port)
        if native_available() and global_config().direct_call:
            from ray_tpu import _native
            from ray_tpu._private.rpc import _NativeEngine

            self._engine = _NativeEngine.for_running_loop()
            self._fastlane = _native.load_fastlane()
        self.controller = RpcClient(
            self.controller_addr, name="to-controller", auto_reconnect=True
        )
        self.controller.chaos_peer = "controller"
        await self.controller.connect()
        self.agent = RpcClient(self.agent_addr, name="to-agent")
        self.agent.chaos_peer = f"node:{self.node_id}"
        await self.agent.connect()
        # Replayed after a controller restart (gcs_client reconnect role).
        self.controller.on_reconnect = self._controller_handshake
        await self._controller_handshake()

    async def _controller_handshake(self) -> None:
        await self.controller.call(
            "register_client",
            {
                "worker_id": self.worker_id,
                "job_id": self.job_id,
                "node_id": self.node_id,
                "address": list(self.address),
                "is_driver": self.is_driver,
            },
        )
        if self._subscribed_channels:
            await self.controller.call(
                "subscribe", {"channels": sorted(self._subscribed_channels)}
            )

    async def subscribe_channels(self, channels: list[str]) -> None:
        """Subscribe to controller pubsub channels; re-subscribed
        automatically after a controller restart."""
        self._subscribed_channels.update(channels)
        await self.controller.call("subscribe", {"channels": channels})

    @property
    def store(self) -> ObjectStoreClient:
        if self._store is None:
            with self._store_lock:
                if self._store is None:
                    self._store = ObjectStoreClient(
                        self.store_info["socket"],
                        self.store_info["shm_path"],
                        self.store_info["capacity"],
                    )
        return self._store

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self.io.run(self._shutdown_async(), timeout=5)
        except Exception:  # rtlint: disable=swallowed-exception - shutdown must not raise; io loop may already be gone
            pass
        self.io.stop()

    async def _shutdown_async(self) -> None:
        # Final task-event flush (companion to util/metrics' atexit
        # flush): a short-lived worker exiting under the size/time batch
        # thresholds must not drop the tail of its lifecycle + resource-
        # attribution stream.
        if self._task_events and self.controller is not None:
            slim, self._task_events = self._task_events, []
            events = []
            for task_id, name, state, start_ts, ts, extras in slim:
                event = {
                    "task_id": task_id,
                    "name": name,
                    "state": state,
                    "node_id": self.node_id,
                    "worker_id": self.worker_id,
                    "pid": os.getpid(),
                    "ts": ts,
                }
                if start_ts is not None:
                    event["start_ts"] = start_ts
                if extras:
                    event.update(extras)
                events.append(event)
            try:
                await self.controller.call(
                    "report_task_events", {"events": events}, timeout=2
                )
            except Exception:  # rtlint: disable=swallowed-exception - final task-event flush is advisory at shutdown
                pass
        for addr, owner in list(self._borrowed.items()):
            try:
                client = await self._client_for(tuple(owner))
                await client.call("remove_borrower", {"object_id": addr, "borrower": self.worker_id}, timeout=1)
            except Exception:  # rtlint: disable=swallowed-exception - owner may be gone at shutdown; borrow GC is advisory
                pass
        if self.controller is not None:
            await self.controller.close()
        if self.agent is not None:
            await self.agent.close()
        # Close every outstanding peer client (direct, actor, leased-worker)
        # so their recv loops are reaped — dropping them unclosed leaves
        # "Task was destroyed but it is pending!" noise at exit.
        with self._direct_lock:
            direct_workers = [
                dw for pool in self._direct_pool.values() for dw in pool
            ]
            self._direct_pool.clear()
        for dw in direct_workers:
            try:
                await self._release_lease(dw.leased, reusable=True)
            except Exception:  # rtlint: disable=swallowed-exception - lease release at shutdown; agent may be gone
                pass
        peers = list(self._clients.values())
        for leases in self._idle_leases.values():
            peers.extend(w.client for w in leases if w.client is not None)
        for client in peers:
            try:
                await client.close()
            except Exception:  # rtlint: disable=swallowed-exception - peer close at shutdown
                pass
        self._clients.clear()
        self._idle_leases.clear()
        await self.core_server.stop()

    async def _client_for(self, address: tuple) -> RpcClient:
        address = tuple(address)
        client = self._clients.get(address)
        if client is not None and client.connected:
            return client
        # Single-flight dial per address: a burst of concurrent calls shares
        # ONE connect attempt (and its retry backoff) instead of each dialing
        # its own connection — duplicate dials leaked unclosed recv loops
        # (r2 verdict weak #3), and per-waiter sequential re-dials to a dead
        # peer would serialize N full backoff windows.
        dial = self._client_dials.get(address)
        if dial is None:
            dial = asyncio.get_running_loop().create_task(self._dial(address))
            self._client_dials[address] = dial
            dial.add_done_callback(
                lambda _t, a=address: self._client_dials.pop(a, None)
            )
        # shield: one waiter's cancellation must not abort the shared dial.
        return await asyncio.shield(dial)

    async def _dial(self, address: tuple) -> RpcClient:
        stale = self._clients.get(address)
        client = RpcClient(address, name=f"to-{address}")
        await client.connect()
        self._clients[address] = client
        if stale is not None:
            try:
                await stale.close()
            except Exception:  # rtlint: disable=swallowed-exception - closing a stale superseded connection
                pass
        return client

    # ------------------------------------------------------------------
    # reference counting (N21)
    # ------------------------------------------------------------------
    def add_local_ref(self, object_id: str) -> None:
        with self._refs_lock:
            self._local_refs[object_id] = self._local_refs.get(object_id, 0) + 1

    def remove_local_ref(self, object_id: str) -> None:
        if self._shutdown:
            return
        with self._refs_lock:
            count = self._local_refs.get(object_id, 0) - 1
            if count <= 0:
                self._local_refs.pop(object_id, None)
            else:
                self._local_refs[object_id] = count
                return
        self._maybe_free(object_id)

    def _maybe_free(self, object_id: str) -> None:
        with self._refs_lock:
            if (
                self._local_refs.get(object_id, 0) > 0
                or self._submitted_refs.get(object_id, 0) > 0
                or self._borrowers.get(object_id)
            ):
                return
            owned = object_id in self._objects
        if not owned:
            # We were a borrower: tell the owner we're done.
            owner = self._borrowed.pop(object_id, None)
            if owner is not None:
                self.io.spawn(self._notify_remove_borrower(object_id, owner))
            return
        # Free synchronously on THIS thread: for inline objects (the per-task
        # common case) the whole release is dict pops + an optional native
        # abandon — paying a run_coroutine_threadsafe loop wakeup (~50us on
        # 1-core hosts) per dropped ref would dominate small-task throughput.
        # Only SHM deletion needs the io loop (it's an RPC).
        state = self._objects.pop(object_id, None)
        self._lineage.pop(object_id, None)
        if state is None:
            return
        record = state.record
        if (
            record is not None
            and record.direct
            and not record.done
            and all(rid not in self._objects for rid in record.return_ids)
        ):
            # Fire-and-forget: every ref to this direct-lane task's returns
            # is gone and nobody will ever collect the reply — abandon the
            # native call entry (the task still executes; only the reply
            # is dropped, matching ignored-ref semantics) so the C++ call
            # table, task records, and worker inflight counts don't leak.
            self._direct_abandon(record)
        if state.status != SHM:
            return
        self.io.spawn(self._delete_shm_object(object_id, list(state.locations)))

    async def _notify_remove_borrower(self, object_id: str, owner: tuple) -> None:
        try:
            client = await self._client_for(owner)
            await client.call(
                "remove_borrower", {"object_id": object_id, "borrower": self.worker_id}
            )
        except Exception:  # rtlint: disable=swallowed-exception - owner death invalidates the borrow anyway
            pass

    async def _delete_shm_object(self, object_id: str, locations: list) -> None:
        for loc in locations:
            try:
                client = await self._client_for((loc["agent_host"], loc["agent_port"]))
                await client.call("delete_object", {"object_id": object_id})
            except Exception:  # rtlint: disable=swallowed-exception - delete fan-out; a dead agent holds no object
                pass

    # ------------------------------------------------------------------
    # put / get / wait
    # ------------------------------------------------------------------
    def new_object_ref(self, object_id: str) -> ObjectRef:
        return ObjectRef(object_id, self.address, runtime=self)

    def put(self, value: Any) -> ObjectRef:
        with self._counter_lock:
            self._put_counter += 1
            put_index = self._put_counter
        task_scope = TaskID(f"tsk-{self.worker_id}")
        object_id = ObjectID.for_put(task_scope, put_index)
        parts, total, contained = serialization.serialize_parts(value)
        self._register_contained_borrows(contained)
        state = ObjectState()
        cfg = global_config()
        if total <= cfg.max_direct_call_object_size:
            state.status = INLINE
            state.data = b"".join(
                bytes(p) if isinstance(p, memoryview) else p for p in parts
            )
            state.size = total
        else:
            self._store_put_parts(object_id, parts, total)
            state.status = SHM
            state.size = total
            state.locations = [self._local_location()]
        # Publish directly from this thread: the state is settled before
        # anyone can see it, so setting the (waiterless) event is safe and
        # the put pays no io-loop round-trip.
        state.event.set()
        self._objects[object_id] = state
        return self.new_object_ref(object_id)

    def _store_put_local(self, object_id: str, payload: bytes) -> None:
        try:
            self.store.put(object_id, payload)
            self.store.pin(object_id)
        except FileExistsError:
            pass
        except ObjectStoreFull as exc:
            raise exceptions.ObjectStoreFullError(str(exc)) from None

    def _store_put_parts(self, object_id: str, parts: list, total: int) -> None:
        """Scatter-gather write: stream serialized parts straight into the
        arena allocation (single copy; plasma create/seal discipline)."""
        try:
            view = self.store.create(object_id, total)
            offset = 0
            for part in parts:
                n = part.nbytes if isinstance(part, memoryview) else len(part)
                view[offset : offset + n] = part
                offset += n
            self.store.seal(object_id)
            self.store.pin(object_id)
        except FileExistsError:
            pass
        except ObjectStoreFull as exc:
            raise exceptions.ObjectStoreFullError(str(exc)) from None

    def _local_location(self) -> dict:
        return {
            "node_id": self.node_id,
            "socket": self.store_info["socket"],
            "shm_path": self.store_info["shm_path"],
            "capacity": self.store_info["capacity"],
            "agent_host": self.agent_addr[0],
            "agent_port": self.agent_addr[1],
        }

    def _register_contained_borrows(self, refs: Sequence[ObjectRef]) -> None:
        """Objects nested inside a stored value: keep them alive while the
        outer value exists (simplified nested-ref handling of [N21])."""
        for ref in refs:
            self.add_local_ref(ref.id)  # leak-safe: freed at shutdown

    def get(self, refs: ObjectRef | Sequence[ObjectRef], timeout: float | None = None) -> Any:
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        if self._engine is not None and ref_list:
            values = self._get_direct(ref_list, timeout)
            if values is not _DIRECT_MISS:
                return values[0] if single else values

        async def _gather():
            return await asyncio.wait_for(
                asyncio.gather(*(self._get_one(r) for r in ref_list)), timeout
            )

        try:
            values = self.io.run(_gather())
        except (asyncio.TimeoutError, concurrent.futures.TimeoutError):
            if os.environ.get("RAY_TPU_debug_hang"):
                self._dump_hang_state([r.id for r in ref_list])
            raise exceptions.GetTimeoutError(
                f"get() timed out after {timeout}s"
            ) from None
        return values[0] if single else values

    @staticmethod
    def _conn_debug(client) -> tuple | str:
        """Native-engine wq state of a client's conn (hang forensics)."""
        import ctypes

        engine = getattr(client, "_engine", None)
        conn = getattr(client, "_conn_id", None)
        if engine is None or conn is None:
            return "no-native-conn"
        out = (ctypes.c_longlong * 6)()
        rc = engine.lib.rt_conn_debug(engine.handle, conn, out)
        if rc != 0:
            return "conn-unknown-to-engine"
        return {
            "wq_len": out[0], "woff": out[1], "fd": out[2],
            "closed": out[3], "bytes_queued": out[4],
            "unparsed_rbuf": out[5], "conn_id": conn,
        }

    def _dump_hang_state(self, waiting_ids: list) -> None:
        """RAY_TPU_debug_hang=1: print submitter state when a get times
        out — first tool to reach for on a silent stall. Also appended to
        /tmp/raytpu_hang.log (pytest captures stderr of a test that never
        finishes, which is exactly when this fires)."""
        import sys

        lines = [
            "=== blocked get/wait: submitter state ===",
            f"waiting on: {waiting_ids}",
            "records: "
            + repr(
                {
                    k: (v.done, v.attempts, v.spec.get("name"))
                    for k, v in self._task_records.items()
                }
            ),
            "dispatchers: " + repr(dict(self._active_dispatchers)),
            "hints: " + repr(dict(self._lease_capacity_hint)),
            "queues: "
            + repr({k: q.qsize() for k, q in self._task_queues.items()}),
            "running: "
            + repr(
                {
                    t: (
                        getattr(c, "address", "?"),
                        getattr(c, "connected", "?"),
                        self._conn_debug(c),
                    )
                    for t, c in self._running_tasks.items()
                }
            ),
            "waiting states: "
            + repr(
                {
                    i: getattr(self._objects.get(i), "status", "?")
                    for i in waiting_ids
                }
            ),
        ]
        text = "\n".join(lines)
        print(text, file=sys.stderr)
        try:
            with open("/tmp/raytpu_hang.log", "a") as fh:
                fh.write(text + "\n\n")
        except OSError:
            pass

    def as_future(self, ref: ObjectRef) -> concurrent.futures.Future:
        return asyncio.run_coroutine_threadsafe(self._get_one(ref), self.io.loop)

    async def _get_one(self, ref: ObjectRef) -> Any:
        payload, pinned = await self._resolve_payload(ref)
        return self._deserialize_value(ref.id, payload, pinned)

    async def _resolve_payload(self, ref: ObjectRef) -> tuple[Any, bool]:
        """Returns (payload bytes/memoryview, is_pinned_view)."""
        state = self._objects.get(ref.id)
        if state is not None:
            await self._await_state(state)
            return await self._payload_from_state(ref.id, state)
        # Not the owner: ask the owner (blocks server-side until ready).
        owner = ref.owner_address
        if owner is None:
            raise exceptions.ObjectLostError(f"{ref.id}: no owner address")
        client = await self._client_for(owner)
        try:
            resp = await client.call("get_object", {"object_id": ref.id})
        except (ConnectionLost, RpcError) as exc:
            raise exceptions.ObjectLostError(
                f"{ref.id}: owner {owner} unreachable ({exc})"
            ) from None
        if resp["status"] == "failed":
            self._raise_stored_error(resp["error"])
        if resp["status"] == "inline":
            return resp["data"], False
        # shm
        data = await self._fetch_shm(ref.id, resp["locations"], resp["size"])
        return data, True

    async def _payload_from_state(self, object_id: str, state: ObjectState):
        if state.status == FAILED:
            self._raise_stored_error(state.error)
        if state.status == INLINE:
            return state.data, False
        data = await self._fetch_shm(object_id, state.locations, state.size)
        return data, True

    def _raise_stored_error(self, error_payload) -> None:
        exc = serialization.deserialize(error_payload)
        raise exc

    async def _fetch_shm(self, object_id: str, locations: list[dict], size: int):
        """Local store first; else pull via the remote node's agent
        (object_manager.cc / pull_manager.cc-equivalent path [N16])."""
        view = self.store.get(object_id, timeout_ms=0)
        if view is not None:
            return view
        for loc in locations:
            if loc["node_id"] == self.node_id:
                view = self.store.get(object_id, timeout_ms=2000)
                if view is not None:
                    return view
                continue
            try:
                if tracing.enabled():
                    with tracing.span(
                        "object_pull", object_id=object_id,
                        src_node=loc["node_id"],
                    ) as pspan:
                        data = await self._pull_remote(object_id, loc)
                        if pspan is not None and data is not None:
                            pspan.attributes["bytes"] = len(data)
                else:
                    data = await self._pull_remote(object_id, loc)
            except Exception:  # rtlint: disable=swallowed-exception - location failed: try the next replica
                continue
            if data is not None:
                try:
                    self.store.put(object_id, data)
                except FileExistsError:
                    pass
                except ObjectStoreFull:
                    return data  # serve from heap this once
                view = self.store.get(object_id, timeout_ms=0)
                return view if view is not None else data
        # All copies gone: attempt lineage reconstruction (owner-side only).
        if await self._try_reconstruct(object_id):
            state = self._objects[object_id]
            return (await self._payload_from_state(object_id, state))[0]
        raise exceptions.ObjectLostError(f"{object_id}: all copies lost")

    async def _pull_remote(self, object_id: str, loc: dict) -> bytes | None:
        cfg = global_config()
        client = await self._client_for((loc["agent_host"], loc["agent_port"]))
        chunks: list[bytes] = []
        offset = 0
        while True:
            resp = await client.call(
                "pull_object_chunk",
                {
                    "object_id": object_id,
                    "offset": offset,
                    "chunk": cfg.object_transfer_chunk_bytes,
                },
            )
            if resp["status"] != "ok":
                return None
            chunks.append(resp["data"])
            offset += len(resp["data"])
            if offset >= resp["total"]:
                break
        return b"".join(chunks)

    def _deserialize_value(self, object_id: str, payload, pinned: bool) -> Any:
        def resolver(ref_id: str, owner_address):
            ref = ObjectRef(ref_id, owner_address, runtime=self)
            self._note_borrow(ref_id, owner_address)
            return ref

        if pinned and len(payload) >= _ZERO_COPY_THRESHOLD:
            value = serialization.deserialize(payload, resolver, zero_copy=True)
            try:
                self.store.pin(object_id)
                store = self.store
                weakref.finalize(
                    value, _release_pinned, store, object_id
                )
                self.store.release(object_id)
                return value
            except TypeError:
                pass  # not weakref-able: fall through to copy
        value = serialization.deserialize(payload, resolver, zero_copy=False)
        if pinned:
            try:
                self.store.release(object_id)
            except Exception:  # rtlint: disable=swallowed-exception - release of a ref the store may have evicted
                pass
        return value

    def _note_borrow(self, object_id: str, owner_address) -> None:
        if owner_address is None or tuple(owner_address) == self.address:
            return
        if object_id in self._borrowed:
            return
        self._borrowed[object_id] = tuple(owner_address)
        self.io.spawn(self._register_borrow(object_id, tuple(owner_address)))

    async def _register_borrow(self, object_id: str, owner: tuple) -> None:
        try:
            client = await self._client_for(owner)
            await client.call(
                "add_borrower", {"object_id": object_id, "borrower": self.worker_id}
            )
        except Exception:  # rtlint: disable=swallowed-exception - owner death invalidates the borrow anyway
            pass

    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int = 1,
        timeout: float | None = None,
        fetch_local: bool = True,
    ) -> tuple[list[ObjectRef], list[ObjectRef]]:
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        if timeout is None and os.environ.get("RAY_TPU_debug_hang"):
            # Debug mode: an unbounded wait that exceeds 120s dumps the
            # submitter state once, then resumes waiting (same first-tool
            # role as the get() dump above).
            ready, not_ready = self.io.run(
                self._wait_async(list(refs), num_returns, 120.0)
            )
            if len(ready) >= num_returns:
                return ready, not_ready
            self._dump_hang_state([r.id for r in refs])
        return self.io.run(self._wait_async(list(refs), num_returns, timeout))

    async def _wait_async(self, refs, num_returns, timeout):
        tasks = {
            asyncio.ensure_future(self._wait_ready(ref)): ref for ref in refs
        }
        ready: list[ObjectRef] = []
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = set(tasks.keys())
        while pending and len(ready) < num_returns:
            remaining = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
            done, pending = await asyncio.wait(
                pending, timeout=remaining, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                # Retrieve exceptions: a ref whose owner is unreachable is
                # "ready" in the sense that get() won't block (it will raise
                # immediately) — same semantics the reference gives errored
                # objects in ray.wait.
                task.exception()
                ready.append(tasks[task])
            if deadline is not None and time.monotonic() >= deadline:
                break
        for task in pending:
            task.cancel()
        ready_set = {r.id for r in ready}
        not_ready = [r for r in refs if r.id not in ready_set]
        return ready, not_ready

    async def _wait_ready(self, ref: ObjectRef) -> None:
        state = self._objects.get(ref.id)
        if state is not None:
            await self._await_state(state)
            return
        client = await self._client_for(ref.owner_address)
        await client.call("wait_object", {"object_id": ref.id})

    # ------------------------------------------------------------------
    # direct-call lane — the native per-call hot path (N18/N19).
    #
    # Simple tasks (no ref args, default strategy/runtime-env) and actor
    # calls ride the C++ call table (src/rpc/transport.cc rt_call_*)
    # straight from the calling thread: spec encode (typed wire schema),
    # submit, reply matching, and inline-return settling never touch the
    # asyncio loop. Python keeps ONLY the scheduling policy (lease
    # acquisition via the asyncio path) and failure handling (fallback to
    # the asyncio machinery). Role split mirrors the reference's
    # normal_task_submitter.cc / actor_task_submitter.cc over C++ rpc.
    # ------------------------------------------------------------------
    def _direct_pick(self, key: str, spec: dict) -> "DirectWorker | None":
        """Least-loaded live direct worker for this resource shape, or
        None (caller falls back to the asyncio path). Triggers ASYNC pool
        growth so the next submits find capacity — never blocks."""
        cfg = global_config()
        now = time.monotonic()
        with self._direct_lock:
            pool = self._direct_pool.get(key)
            alive = [w for w in pool if not w.dead] if pool else []
            if pool is not None and len(alive) != len(pool):
                self._direct_pool[key] = alive
            best = min(alive, key=lambda w: w.inflight) if alive else None
            growing = self._direct_grows.get(key, 0)
            backoff_until = self._direct_backoff.get(key, 0.0)
            hint = self._lease_capacity_hint.get(
                key, self._MAX_DISPATCHERS_PER_KEY
            )
            cap = min(self._MAX_DISPATCHERS_PER_KEY, max(1, hint))
            # Grow on the NATIVE in-flight depth (calls still awaiting a
            # reply in the C++ table), not the Python uncollected count: a
            # burst of already-executed-but-not-yet-collected fast tasks
            # must not spawn workers the machine will only thrash between.
            want_grow = best is None or (
                best.inflight >= cfg.worker_pipeline_depth
                and len(alive) + growing < cap
                and self._engine.pylib.rt_conn_inflight(
                    self._engine.handle, best.conn_id
                ) >= cfg.worker_pipeline_depth
            )
            if (
                want_grow
                and now >= backoff_until
                and growing < 2
                and len(alive) + growing < cap
            ):
                self._direct_grows[key] = growing + 1
                self.io.spawn(self._direct_grow(key, dict(spec)))
            if best is not None:
                best.inflight += 1
                best.last_used = now
            return best

    async def _direct_grow(self, key: str, spec: dict) -> None:
        try:
            leased = await self._acquire_lease(spec)
            conn_id = getattr(leased.client, "_conn_id", None)
            if conn_id is None:  # asyncio-backend client: lane unusable
                await self._release_lease(leased, reusable=True)
                return
            dw = DirectWorker(leased, conn_id)
            with self._direct_lock:
                self._direct_pool.setdefault(key, []).append(dw)
            if not self._direct_reaper_started:
                self._direct_reaper_started = True
                spawn_task(self._direct_reaper())
        except Exception:
            # No capacity: back off so a hot submit loop doesn't churn
            # controller lease RPCs (the dispatcher's capacity-hint role).
            with self._direct_lock:
                self._direct_backoff[key] = time.monotonic() + 2.0
        finally:
            with self._direct_lock:
                self._direct_grows[key] = max(
                    0, self._direct_grows.get(key, 1) - 1
                )

    async def _direct_reaper(self) -> None:
        """Idle direct leases return to the agent after the grace period
        (raylet idle-lease grace role) so pool resources never strand."""
        grace = global_config().worker_lease_grace_s
        while not self._shutdown:
            await asyncio.sleep(max(grace, 0.1))
            now = time.monotonic()
            to_release = []
            with self._direct_lock:
                for key, pool in list(self._direct_pool.items()):
                    keep = []
                    for dw in pool:
                        if dw.dead:
                            continue
                        if dw.inflight == 0 and now - dw.last_used > grace:
                            to_release.append(dw)
                        else:
                            keep.append(dw)
                    self._direct_pool[key] = keep
            for dw in to_release:
                try:
                    await self._release_lease(dw.leased, reusable=True)
                except Exception:  # rtlint: disable=swallowed-exception - idle lease release; agent may be gone
                    pass

    def _direct_note_dead(self, dw: DirectWorker) -> None:
        dw.dead = True
        with self._direct_lock:
            pool = self._direct_pool.get(dw.leased.resources_key)
            if pool and dw in pool:
                pool.remove(dw)
        try:
            self.io.spawn(self._release_lease(dw.leased, reusable=False))
        except RuntimeError:
            pass

    def _direct_submit(
        self, key: str, record: PendingTask, parts: tuple | None = None
    ) -> bool:
        """Put a simple task on the wire via the native call table from
        THIS thread. False = caller must use the asyncio path."""
        engine = self._engine
        if engine is None:
            return False
        worker = self._direct_pick(key, record.spec)
        if worker is None:
            return False
        fl = self._fastlane
        if fl is not None and parts is not None:
            # One C call: splice the canonical payload from the precompiled
            # template parts + start the native call (buffered in bursts).
            handle = fl.submit(
                engine.handle, worker.conn_id, b"push_task",
                parts[0], record.spec["task_id"], parts[1],
                record.spec["args"], parts[2], 0, -1,
                1 if self._direct_unsettled >= 2 else 0,
            )
        else:
            payload = wire_gen.encode_task_spec(record.spec)
            lib = (
                engine.pylib
                if len(payload) < engine._PYLIB_MAX_PAYLOAD
                else engine.lib
            )
            starter = (
                lib.rt_call_start_buf
                if self._direct_unsettled >= 2
                else lib.rt_call_start
            )
            handle = starter(
                engine.handle, worker.conn_id, b"push_task", 9,
                payload, len(payload),
            )
        if handle == 0:
            with self._direct_lock:
                worker.inflight -= 1
            self._direct_note_dead(worker)
            return False
        self._direct_unsettled += 1
        record.make_direct()
        record.attempts = 1
        record.native_handle = handle
        record.direct_worker = worker
        for rid in record.return_ids:
            state = self._objects.get(rid)
            if state is not None:
                state.record = record
        self._running_tasks[record.spec["task_id"]] = worker.leased.client
        return True

    def _settle_native(
        self, record: PendingTask, timeout: float | None
    ) -> bool:
        """Drive a direct-lane record to completion from THIS thread
        (blocking, GIL released inside rt_call_wait). True = settled;
        False = timeout. Safe under contention: the first settler consumes
        the native handle, everyone else waits on record.done_event."""
        import ctypes

        from ray_tpu import _native

        deadline = None if timeout is None else time.monotonic() + timeout
        engine = self._engine
        while not record.done:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
            acquired = record.settle_lock.acquire(
                timeout=-1 if remaining is None else remaining
            )
            if not acquired:
                return False
            settled_here = False
            try:
                if record.done:
                    return True
                handle = record.native_handle
                if handle is not None:
                    timeout_ms = (
                        -1 if remaining is None else max(1, int(remaining * 1000))
                    )
                    fl = self._fastlane
                    if fl is not None:
                        # C-side wait + reply decode: the common ok/inline
                        # case comes back as ready-to-store bytes.
                        res = fl.call_wait(engine.handle, handle, timeout_ms)
                        rc = res[0]
                        if rc == 0:
                            return False
                        record.native_handle = None
                        self._direct_unsettled = max(
                            0, self._direct_unsettled - 1
                        )
                        if rc == 1:
                            settled_here = self._direct_reply_inline(
                                record, res[1]
                            )
                        elif rc == 2:
                            settled_here = self._direct_reply(
                                record, REP, res[1]
                            )
                        elif rc == 3:
                            settled_here = self._direct_reply(
                                record, ERR, res[1]
                            )
                        elif rc == -1:
                            settled_here = self._direct_conn_lost(record)
                        # rc == -2: someone else consumed the handle —
                        # fall through to done_event below.
                    else:
                        view = _native.RtMsgView()
                        rc = engine.lib.rt_call_wait(
                            engine.handle, handle, timeout_ms,
                            ctypes.byref(view),
                        )
                        if rc == 0:
                            return False
                        record.native_handle = None
                        self._direct_unsettled = max(
                            0, self._direct_unsettled - 1
                        )
                        if rc == 1:
                            kind = view.kind
                            raw = (
                                ctypes.string_at(view.payload, view.plen)
                                if view.plen
                                else b""
                            )
                            engine.pylib.rt_msg_free(view.opaque)
                            settled_here = self._direct_reply(
                                record, kind, raw
                            )
                        elif rc == -1:
                            settled_here = self._direct_conn_lost(record)
                        # rc == -2: someone else consumed the handle — fall
                        # through to done_event below.
            finally:
                record.settle_lock.release()
            if settled_here or record.done:
                return True
            # The record is now owned by the asyncio machinery (retry /
            # actor protocol): wait for _finish_record / _run_actor_task.
            wait_s = None
            if deadline is not None:
                wait_s = max(0.0, deadline - time.monotonic())
            if not record.done_event.wait(wait_s):
                return False
        return True

    def _direct_reply_inline(self, record: PendingTask, data: bytes) -> bool:
        """Slim settle for the dominant reply shape (status ok, one inline
        return, already isolated by the C-side scan): store the bytes and
        finish the record without building a reply dict. Mirrors
        _direct_reply + _finish_record for that shape exactly."""
        if len(record.return_ids) != 1:
            # Expected-returns mismatch: take the generic path (it zips
            # and fails/fills per state like the asyncio machinery).
            return self._direct_reply(
                record,
                REP,
                wire_gen.encode_task_reply(
                    {"status": "ok",
                     "returns": [{"kind": "inline", "data": data}]}
                ),
            )
        dw = record.direct_worker
        if dw is not None:
            record.direct_worker = None
            with self._direct_lock:
                dw.inflight -= 1
                dw.last_used = time.monotonic()
        spec = record.spec
        task_id = spec["task_id"]
        self._running_tasks.pop(task_id, None)
        if record.done:
            return True
        record.done = True
        self._task_records.pop(task_id, None)
        self._cancelled_tasks.discard(task_id)
        state = self._objects.get(record.return_ids[0])
        if state is not None:
            state.status = INLINE
            state.data = data
            state.size = len(data)
            state.record = None
            self._set_state_event(state)
        if record.done_event is not None:
            record.done_event.set()
        if record.arg_refs:
            with self._refs_lock:
                for rid in record.arg_refs:
                    count = self._submitted_refs.get(rid, 0) - 1
                    if count <= 0:
                        self._submitted_refs.pop(rid, None)
                    else:
                        self._submitted_refs[rid] = count
            for rid in record.arg_refs:
                self._maybe_free(rid)
        return True

    def _direct_reply(self, record: PendingTask, kind: int, raw: bytes) -> bool:
        """Apply a native reply frame. True = record finished; False =
        requeued through the asyncio path (retry_exceptions)."""
        dw = record.direct_worker
        if dw is not None:
            record.direct_worker = None
            with self._direct_lock:
                dw.inflight -= 1
                dw.last_used = time.monotonic()
        spec = record.spec
        task_id = spec["task_id"]
        self._running_tasks.pop(task_id, None)
        if kind == ERR:
            self._finish_record(
                record,
                error=exceptions.WorkerCrashedError(
                    f"task {spec['name']}: remote dispatch error: "
                    f"{raw[:300]!r}"
                ),
            )
            return True
        reply = wire_gen.decode_task_reply(raw)
        if reply["status"] == "cancelled":
            self._finish_record(
                record,
                error=exceptions.TaskCancelledError(
                    f"task {spec['name']} was cancelled"
                ),
            )
            return True
        if (
            reply["status"] == "error"
            and spec.get("retry_exceptions")
            and record.attempts <= spec.get("max_retries", 0)
            and task_id not in self._cancelled_tasks
            and not spec.get("actor_id")
        ):
            try:
                self.io.loop.call_soon_threadsafe(self._enqueue_task, record)
                return False
            except RuntimeError:
                pass
        self._finish_record(record, reply=reply)
        return True

    def _direct_conn_lost(self, record: PendingTask) -> bool:
        """Native call failed with connection loss: apply the same policy
        as the asyncio submitter (_push_one / _run_actor_task). True =
        record finished here; False = handed to the asyncio machinery."""
        dw = record.direct_worker
        if dw is not None:
            record.direct_worker = None
            with self._direct_lock:
                dw.inflight -= 1
            self._direct_note_dead(dw)
        spec = record.spec
        task_id = spec["task_id"]
        self._running_tasks.pop(task_id, None)
        if task_id in self._cancelled_tasks:
            self._finish_record(
                record,
                error=exceptions.WorkerCrashedError(
                    f"task {spec['name']} force-cancelled"
                ),
            )
            return True
        if spec.get("actor_id"):
            # Actor protocol (controller consult / restart retry) lives in
            # _run_actor_task — replay the record through it.
            try:
                self.io.loop.call_soon_threadsafe(
                    lambda: spawn_task(self._run_actor_task(record))
                )
                return False
            except RuntimeError:
                pass
        elif record.attempts <= spec.get("max_retries", 0):
            try:
                self.io.loop.call_soon_threadsafe(self._enqueue_task, record)
                return False
            except RuntimeError:
                pass
        elif dw is not None:
            # Final failure: attribute the death (OOM vs crash) on the io
            # loop — the tombstone query is an RPC. Caller waits on
            # done_event; _finish_record sets it.
            leased = dw.leased

            async def _finish_attributed():
                self._finish_record(
                    record,
                    error=await self._worker_failure_error(
                        leased, spec, record.attempts,
                        "connection to worker lost",
                    ),
                )

            try:
                self.io.loop.call_soon_threadsafe(
                    lambda: spawn_task(_finish_attributed())
                )
                return False
            except RuntimeError:
                pass
        self._finish_record(
            record,
            error=exceptions.WorkerCrashedError(
                f"task {spec['name']} failed after {record.attempts} "
                f"attempts: connection to worker lost"
            ),
        )
        return True

    def _direct_abandon(self, record: PendingTask) -> None:
        """Release a direct-lane record nobody will settle (all return
        refs dropped). Safe: with zero live refs there can be no
        concurrent settler (settlers hold a ref)."""
        with record.settle_lock:
            if record.done:
                return
            handle = record.native_handle
            record.native_handle = None
            if handle is not None:
                engine = self._engine
                if engine is not None and engine.handle:
                    engine.pylib.rt_call_abandon(engine.handle, handle)
                self._direct_unsettled = max(0, self._direct_unsettled - 1)
            dw = record.direct_worker
            record.direct_worker = None
            if dw is not None:
                with self._direct_lock:
                    dw.inflight -= 1
                    dw.last_used = time.monotonic()
            record.done = True
            task_id = record.spec.get("task_id")
            self._task_records.pop(task_id, None)
            self._running_tasks.pop(task_id, None)
            if record.done_event is not None:
                record.done_event.set()

    async def _settle_native_async(self, record: PendingTask) -> None:
        """Loop-side access to a direct-lane record: drive completion on
        an executor thread (rt_call_wait must never block the io loop)."""
        if record.done:
            return
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self._settle_native, record, None)

    async def _await_state(self, state: ObjectState) -> None:
        """Wait until `state` settles, driving direct-lane records to
        completion (their replies sit in the C++ call table until someone
        collects — a bare event.wait would park forever)."""
        if state.status != PENDING:
            return
        record = state.record
        if record is not None and record.direct:
            await self._settle_native_async(record)
            return
        state.waited = True
        if state.status != PENDING:  # settled between check and flag
            return
        await state.event.wait()

    def _get_direct(self, ref_list, timeout):
        """All-local fast get: settle direct-lane records and read local
        payloads entirely on the calling thread. Returns _DIRECT_MISS to
        fall back to the asyncio path for anything it cannot prove local
        (partial settling is fine — the asyncio path is idempotent)."""
        states = []
        for ref in ref_list:
            state = self._objects.get(ref.id)
            if state is None:
                return _DIRECT_MISS
            if state.status == PENDING and (
                state.record is None or not state.record.direct
            ):
                return _DIRECT_MISS
            states.append(state)
        deadline = None if timeout is None else time.monotonic() + timeout
        for ref, state in zip(ref_list, states):
            while state.status == PENDING:
                record = state.record
                if record is None or not record.direct:
                    return _DIRECT_MISS
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise exceptions.GetTimeoutError(
                            f"get() timed out after {timeout}s"
                        )
                if not self._settle_native(record, remaining):
                    if os.environ.get("RAY_TPU_debug_hang"):
                        self._dump_hang_state([r.id for r in ref_list])
                    raise exceptions.GetTimeoutError(
                        f"get() timed out after {timeout}s"
                    )
        values = []
        for ref, state in zip(ref_list, states):
            if state.status == FAILED:
                self._raise_stored_error(state.error)
            if state.status == INLINE:
                values.append(
                    self._deserialize_value(ref.id, state.data, False)
                )
                continue
            # SHM: serve only local-store hits on this thread.
            view = self.store.get(ref.id, timeout_ms=0)
            if view is None:
                local = any(
                    loc.get("node_id") == self.node_id
                    for loc in state.locations
                )
                view = (
                    self.store.get(ref.id, timeout_ms=2000) if local else None
                )
            if view is None:
                return _DIRECT_MISS
            values.append(self._deserialize_value(ref.id, view, True))
        return values

    # ------------------------------------------------------------------
    # task submission (N19/N22)
    # ------------------------------------------------------------------
    def next_task_id(self) -> TaskID:
        with self._counter_lock:
            self._task_counter += 1
            return TaskID(f"tsk-{self.worker_id[4:]}-{self._task_counter}")

    def make_spec_template(
        self,
        *,
        function_id: str,
        name: str,
        num_returns: int = 1,
        resources: dict | None = None,
        max_retries: int | None = None,
        retry_exceptions: bool = False,
        runtime_env: dict | None = None,
        scheduling_strategy: Any = None,
    ) -> dict:
        """Static spec fields for a (function, options) pair — cached by
        RemoteFunction so each submit pays one dict copy, not a rebuild
        (the reference caches its TaskSpec builder the same way)."""
        cfg = global_config()
        template = {
            "task_id": "",
            "job_id": self.job_id,
            "function_id": function_id,
            "name": name,
            "args": b"",
            "num_returns": num_returns,
            "resources": resources or {"CPU": 1},
            "owner": {"worker_id": self.worker_id, "address": list(self.address)},
            "runtime_env": runtime_env or {},
            "scheduling_strategy": _encode_strategy(scheduling_strategy),
            "max_retries": (
                cfg.task_max_retries_default if max_retries is None else max_retries
            ),
            "retry_exceptions": retry_exceptions,
            "has_ref_args": False,
        }
        # Precompiled splice parts: the direct lane re-encodes only
        # (task_id, args) per submit. Computed BEFORE the private keys
        # below join the dict — unknown keys would pass through to p2.
        template["_parts"] = wire_gen.make_task_spec_parts(template)
        # direct-pool key, precomputed (popped before the wire)
        template["_dkey"] = _resources_key(
            resources or {"CPU": 1}, repr(runtime_env or {})
        )
        # dispatcher-queue key, also template-static: at 100k queued
        # tasks the per-submit repr() rebuilds in _enqueue_task dominate
        # the enqueue path, so pay them once per (function, options).
        template["_qkey"] = template["_dkey"] + repr(
            sorted((template["scheduling_strategy"] or {}).items())
        )
        return template

    def submit_task(
        self,
        *,
        function_id: str = "",
        name: str = "",
        args: tuple = (),
        kwargs: dict | None = None,
        num_returns: int = 1,
        resources: dict | None = None,
        max_retries: int | None = None,
        retry_exceptions: bool = False,
        runtime_env: dict | None = None,
        scheduling_strategy: Any = None,
        spec_template: dict | None = None,
    ) -> list[ObjectRef]:
        task_id = self.next_task_id()
        if not args and not kwargs:
            payload, contained = serialization.EMPTY_ARGS_PAYLOAD, ()
        else:
            payload, contained = serialization.serialize((args, kwargs or {}))
        arg_ref_ids = [r.id for r in contained]
        # Submitted-task references: args stay alive until the task finishes.
        if arg_ref_ids:
            with self._refs_lock:
                for rid in arg_ref_ids:
                    self._submitted_refs[rid] = (
                        self._submitted_refs.get(rid, 0) + 1
                    )
        if spec_template is not None:
            spec = dict(spec_template)
            num_returns = spec["num_returns"]
        else:
            spec = self.make_spec_template(
                function_id=function_id,
                name=name,
                num_returns=num_returns,
                resources=resources,
                max_retries=max_retries,
                retry_exceptions=retry_exceptions,
                runtime_env=runtime_env,
                scheduling_strategy=scheduling_strategy,
            )
        direct_key = spec.pop("_dkey", None)
        queue_key = spec.pop("_qkey", None)
        spec_parts = spec.pop("_parts", None)
        return_ids = [
            ObjectID.for_task_return(task_id, i) for i in range(num_returns)
        ]
        spec["task_id"] = task_id
        spec["args"] = payload
        # Workers use this hint to route ref-carrying tasks off the fast
        # execution lane (dependency resolution must not block the main
        # lane — see worker_proc).
        spec["has_ref_args"] = bool(arg_ref_ids)
        submit_span = None
        if tracing.enabled():
            # Submit span: its context rides in the spec so the worker's
            # execute span becomes this one's child (SURVEY §5.1). Uses
            # the begin/finish fast path — this runs once per task on the
            # submitting thread, and the span closes after the handoff to
            # the io loop so it covers the whole client-side submit cost.
            submit_span = tracing.begin(
                f"submit {spec['name']}", task_id=task_id
            )
            spec["trace_ctx"] = {
                "trace_id": submit_span.trace_id,
                "span_id": submit_span.span_id,
            }
        record = PendingTask(spec, return_ids, arg_ref_ids)
        record.queue_key = queue_key
        self._task_records[task_id] = record
        refs = []
        for rid in return_ids:
            state = ObjectState()
            self._objects[rid] = state
            if global_config().lineage_pinning_enabled:
                self._lineage[rid] = record
            refs.append(self.new_object_ref(rid))
        # Direct lane: simple tasks ride the native call table from this
        # very thread — no loop handoff, no dispatcher (N19 direct calls).
        if (
            self._engine is not None
            and not arg_ref_ids
            and not spec["scheduling_strategy"]
            and not spec["runtime_env"]
            and "trace_ctx" not in spec
        ):
            if direct_key is None:
                direct_key = _resources_key(
                    spec["resources"], repr(spec["runtime_env"])
                )
            if self._direct_submit(direct_key, record, spec_parts):
                return refs
        # Batched handoff to the io loop: appending to a deque and waking
        # the loop once per burst (scheduled only on the empty->nonempty
        # edge, under a lock so concurrent submitters can't both skip the
        # wakeup) costs ~1 loop wakeup per BATCH of submits instead of one
        # run_coroutine_threadsafe (~100 us measured on 1-core hosts) per
        # task.
        with self._submit_lock:
            self._submit_buf.append(record)
            need_schedule = not self._submit_scheduled
            self._submit_scheduled = True
        if need_schedule:
            self.io.loop.call_soon_threadsafe(self._drain_submit_buf)
        if submit_span is not None:
            tracing.finish(submit_span)
        return refs

    # The submitter keeps a per-(resources, runtime_env) task queue drained by
    # dispatcher coroutines that each hold one worker lease and pipeline tasks
    # through it — the lease-reuse behavior of normal_task_submitter.cc.
    _MAX_DISPATCHERS_PER_KEY = 16

    def _drain_submit_buf(self) -> None:
        """Runs on the io loop: moves buffered records into their queues."""
        while True:
            with self._submit_lock:
                if not self._submit_buf:
                    self._submit_scheduled = False
                    return
                record = self._submit_buf.popleft()
            self._enqueue_task(record)

    def _enqueue_task(self, record: PendingTask) -> None:
        spec = record.spec
        key = record.queue_key
        if key is None:
            strategy = spec.get("scheduling_strategy") or {}
            key = _resources_key(
                spec["resources"], repr(spec["runtime_env"])
            ) + repr(sorted(strategy.items()))
        queue = self._task_queues.get(key)
        if queue is None:
            queue = self._task_queues[key] = asyncio.Queue()
        queue.put_nowait(record)
        active = self._active_dispatchers.get(key, 0)
        # Dispatcher spawn policy: bounded by queue depth, the hard cap, and
        # the learned capacity hint — when lease acquisition came back
        # "busy" at N holders, spawning an (N+1)-th dispatcher just churns
        # controller lease RPCs. Probe past the hint occasionally so the
        # hint recovers when the cluster grows.
        hint = self._lease_capacity_hint.get(key, self._MAX_DISPATCHERS_PER_KEY)
        self._enqueue_counter += 1
        if self._enqueue_counter % 64 == 0:
            hint += 1  # periodic probe beyond the learned capacity
        if active < min(queue.qsize(), self._MAX_DISPATCHERS_PER_KEY, hint):
            self._active_dispatchers[key] = active + 1
            spawn_task(self._dispatcher(key, queue))

    async def _dispatcher(self, key: str, queue: asyncio.Queue) -> None:
        """Holds one worker lease and PIPELINES tasks through it: up to
        ``worker_pipeline_depth`` pushes in flight before awaiting replies
        (normal_task_submitter pipelining role) — per-task wakeups and
        syscalls amortize across the window."""
        worker: LeasedWorker | None = None
        lease_failures = 0
        inflight: set = set()  # asyncio.Tasks running _push_one

        async def drain_one() -> None:
            # Await one completion; a lost result names the worker that
            # died — drop that lease ONLY if it is still the current one
            # (a stale loss from an already-replaced worker must not
            # release the healthy replacement lease).
            nonlocal worker, inflight
            done, inflight = await asyncio.wait(
                inflight, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                lost = task.result()
                if lost is not None and lost is worker:
                    await self._release_lease(worker, reusable=False)
                    worker = None

        try:
            while True:
                if worker is None:
                    if queue.empty():
                        if inflight:
                            await drain_one()
                            continue
                        return
                    # Acquire BEFORE popping so a blocked acquire (e.g. the
                    # agent queueing lease requests while it spawns
                    # workers) never holds a task hostage — other
                    # dispatchers keep draining the queue meanwhile.
                    spec_peek = queue._queue[0].spec  # safe: single loop
                    try:
                        worker = await self._acquire_lease(spec_peek)
                        lease_failures = 0
                        # Raise a LEARNED hint when concurrency above it
                        # succeeds (e.g. the cluster grew); an absent hint
                        # already means "uncapped" — never lower it here.
                        hint = self._lease_capacity_hint.get(key)
                        active = self._active_dispatchers.get(key, 1)
                        if hint is not None and active > hint:
                            self._lease_capacity_hint[key] = active
                    except Exception as exc:
                        lease_failures += 1
                        if self._active_dispatchers.get(key, 1) > 1:
                            # Learn the capacity: the other holders ARE the
                            # cluster's current parallelism for this shape,
                            # and this excess dispatcher exits rather than
                            # churning controller lease RPCs.
                            self._lease_capacity_hint[key] = max(
                                1, self._active_dispatchers.get(key, 1) - 1
                            )
                            return
                        if lease_failures >= 5:
                            # Can't get capacity: fail one task and keep
                            # trying so an infeasible queue eventually
                            # drains with errors rather than hanging.
                            try:
                                record = queue.get_nowait()
                            except asyncio.QueueEmpty:
                                return
                            self._finish_record(
                                record,
                                error=exceptions.WorkerCrashedError(
                                    f"task {record.spec['name']}: no worker "
                                    f"lease after {lease_failures} attempts: {exc}"
                                ),
                            )
                            lease_failures = 0
                            continue
                        await asyncio.sleep(min(0.2 * lease_failures, 2.0))
                    continue
                try:
                    record = queue.get_nowait()
                except asyncio.QueueEmpty:
                    if inflight:
                        await drain_one()
                        continue
                    # Keep the lease warm for a grace period: the next
                    # same-shape task (e.g. a sync submit loop) reuses this
                    # worker with zero lease RPCs (the raylet's idle lease
                    # grace / lease-reuse role).
                    try:
                        record = await asyncio.wait_for(
                            queue.get(), global_config().worker_lease_grace_s
                        )
                    except (asyncio.TimeoutError, TimeoutError):
                        return
                if record.done or record.spec["task_id"] in self._cancelled_tasks:
                    # cancel() already failed the returns while we queued.
                    continue
                if not inflight and queue.empty():
                    # Sequential fast path (sync submit loops): await the
                    # push directly — no task object, no asyncio.wait
                    # machinery, identical latency to an inline call.
                    lost = await self._push_one(worker, queue, record)
                    if lost is not None and lost is worker:
                        await self._release_lease(worker, reusable=False)
                        worker = None
                    continue
                inflight.add(spawn_task(self._push_one(worker, queue, record)))
                if len(inflight) >= global_config().worker_pipeline_depth:
                    await drain_one()
        finally:
            if inflight:
                await asyncio.wait(inflight)
            self._active_dispatchers[key] = self._active_dispatchers.get(key, 1) - 1
            if worker is not None:
                await self._release_lease(worker, reusable=True)
            # Self-heal: retries requeued during teardown (e.g. from the
            # inflight wait above) must not strand in a dispatcher-less
            # queue until some unrelated future submit of the same key.
            if not queue.empty() and self._active_dispatchers.get(key, 0) <= 0:
                self._active_dispatchers[key] = 1
                spawn_task(self._dispatcher(key, queue))

    # (direct-lane records that fall back re-enter through _enqueue_task;
    # their done_event is set by _finish_record when the asyncio side
    # settles them.)

    def _maybe_push_args(self, record: PendingTask, worker: LeasedWorker) -> None:
        """Submit-time locality hints (push_manager.cc role): large SHM
        args this driver owns that have no copy on the target worker's
        node are pushed agent→agent (C++ chunk plane) while the task
        travels — by the time the worker resolves its args, the bytes are
        usually already local. Fire-and-forget: pull remains the
        fallback."""
        if not record.arg_refs:
            return
        cfg = global_config()
        if not cfg.push_transfers_enabled:
            return
        target = tuple(worker.agent_addr or ())
        if len(target) != 2 or target == tuple(self.agent_addr):
            return
        for rid in record.arg_refs:
            state = self._objects.get(rid)
            if (
                state is None
                or state.status != SHM
                or state.size < cfg.push_transfer_min_bytes
                or not state.locations
            ):
                continue
            if any(
                (loc.get("agent_host"), loc.get("agent_port")) == target
                for loc in state.locations
            ):
                continue  # already local to the target node
            self.io.spawn(self._push_hint(rid, state.locations[0], target))

    async def _push_hint(self, object_id: str, src: dict, target: tuple) -> None:
        try:
            client = await self._client_for(
                (src["agent_host"], src["agent_port"])
            )
            scope = (
                tracing.span(
                    "object_push", object_id=object_id,
                    src_node=src.get("node_id"), dst=f"{target[0]}:{target[1]}",
                )
                if tracing.enabled()
                else contextlib.nullcontext()
            )
            with scope:
                await client.call(
                    "push_object",
                    {
                        "object_id": object_id,
                        "target_host": target[0],
                        "target_port": target[1],
                    },
                    timeout=60,
                )
        except Exception:  # rtlint: disable=swallowed-exception - opportunistic push; pull path still serves the object
            pass  # opportunistic: the pull path still serves the object

    async def _push_one(
        self, worker: LeasedWorker, queue: asyncio.Queue, record: PendingTask
    ) -> "LeasedWorker | None":
        """Push one task to a leased worker and settle its record.
        Returns the worker when its connection died (so the dispatcher can
        drop exactly that lease), else None; on loss this record was
        requeued/failed here according to its retry budget."""
        spec = record.spec
        task_id = spec["task_id"]
        record.attempts += 1
        self._running_tasks[task_id] = worker.client
        self._maybe_push_args(record, worker)
        try:
            reply = await worker.client.call("push_task", spec)
        except (ConnectionLost, RpcError, OSError) as exc:
            if task_id in self._cancelled_tasks:
                # force=True cancellation kills the worker; surface the
                # reference's WorkerCrashedError, never retry.
                self._finish_record(
                    record,
                    error=exceptions.WorkerCrashedError(
                        f"task {spec['name']} force-cancelled"
                    ),
                )
            elif record.attempts <= spec["max_retries"]:
                queue.put_nowait(record)
            else:
                self._finish_record(
                    record,
                    error=await self._worker_failure_error(
                        worker, spec, record.attempts, exc
                    ),
                )
            return worker
        except Exception as exc:  # never kill the dispatcher silently
            traceback.print_exc()
            self._finish_record(
                record,
                error=exceptions.WorkerCrashedError(
                    f"task {spec['name']}: submitter error: {exc!r}"
                ),
            )
            return None
        finally:
            self._running_tasks.pop(task_id, None)
        if reply.get("status") == "cancelled":
            self._finish_record(
                record,
                error=exceptions.TaskCancelledError(
                    f"task {spec['name']} was cancelled"
                ),
            )
            return None
        if (
            reply.get("status") == "error"
            and spec["retry_exceptions"]
            and record.attempts <= spec["max_retries"]
            and task_id not in self._cancelled_tasks
        ):
            queue.put_nowait(record)
            return None
        self._finish_record(record, reply=reply)
        return None

    async def _worker_failure_error(
        self, worker: "LeasedWorker", spec: dict, attempts: int, exc
    ) -> Exception:
        """Attribute a worker death: the node agent's memory monitor
        leaves a tombstone, so an OOM kill surfaces as the distinct
        (retriable, system-level) OutOfMemoryError instead of a generic
        crash (reference memory_monitor.cc / raylet OOM policy, N15).
        The tombstone may land moments after the conn drops — poll
        briefly."""
        reason = rss = None
        try:
            agent = await self._client_for(worker.agent_addr)
            for _ in range(8):
                info = await agent.call(
                    "worker_death_info",
                    {"worker_id": worker.worker_id},
                    timeout=5,
                )
                detail = info.get("info")
                if detail:
                    reason = detail.get("reason")
                    rss = detail.get("rss")
                    break
                if info.get("alive"):
                    break  # no death, no tombstone coming — stop polling
                await asyncio.sleep(0.25)
        except Exception:  # rtlint: disable=swallowed-exception - death-info poll only enriches the error message
            pass
        if reason == "oom":
            mib = f" (rss {rss >> 20} MiB)" if rss else ""
            return exceptions.OutOfMemoryError(
                f"task {spec['name']}: worker {worker.worker_id} was killed "
                f"by the node memory monitor{mib} after {attempts} attempts"
            )
        return exceptions.WorkerCrashedError(
            f"task {spec['name']} failed after {attempts} attempts: {exc}"
        )

    def _finish_record(
        self,
        record: PendingTask,
        reply: dict | None = None,
        error: Exception | None = None,
    ) -> None:
        if record.done:
            return
        record.done = True
        task_id = record.spec.get("task_id")
        self._task_records.pop(task_id, None)
        self._cancelled_tasks.discard(task_id)
        if error is not None:
            self._fail_returns(record, error)
        else:
            self._apply_reply(record, reply)
        if record.done_event is not None:
            record.done_event.set()
        with self._refs_lock:
            for rid in record.arg_refs:
                count = self._submitted_refs.get(rid, 0) - 1
                if count <= 0:
                    self._submitted_refs.pop(rid, None)
                else:
                    self._submitted_refs[rid] = count
        for rid in record.arg_refs:
            self._maybe_free(rid)

    def cancel(self, ref, force: bool = False) -> None:
        """Best-effort task cancellation (reference: CoreWorker::CancelTask;
        semantics of python/ray/tests/test_cancel.py): a queued task is
        dequeued and its refs fail with TaskCancelledError; a running task
        gets KeyboardInterrupt raised in its executing thread (force=False)
        or its worker process SIGKILLed (force=True, refs fail with
        WorkerCrashedError); a finished task is a no-op."""
        self.io.run(self._cancel_async(ref.id, force))

    async def _cancel_async(self, obj_id: str, force: bool) -> None:
        oid = ObjectID(obj_id)
        task_id = oid.creating_task_id()
        # for_put ids also embed a task id; only task RETURNS ("-rN") are
        # cancellable (reference: ray.cancel rejects ray.put refs).
        if task_id is None or not obj_id.rsplit("-", 1)[-1].startswith("r"):
            raise ValueError("only task-return refs can be cancelled")
        state = self._objects.get(obj_id)
        if state is not None and state.status != PENDING:
            return  # already finished: no-op
        self._cancelled_tasks.add(task_id)
        client = self._running_tasks.get(task_id)
        if client is not None:
            try:
                await client.call(
                    "cancel_task", {"task_id": task_id, "force": force},
                    timeout=5,
                )
            except Exception:  # rtlint: disable=swallowed-exception - worker died (force) or finished concurrently
                pass  # worker died (force) or finished concurrently
            return
        record = self._task_records.get(task_id)
        if record is not None:
            self._finish_record(
                record,
                error=exceptions.TaskCancelledError(
                    f"task {record.spec['name']} was cancelled before it started"
                ),
            )

    async def _acquire_lease(self, spec: dict) -> LeasedWorker:
        key = _resources_key(spec["resources"], repr(spec["runtime_env"]))
        strategy = spec.get("scheduling_strategy") or {}
        assert self.controller is not None
        # Carry the triggering task's trace context into the control plane
        # so controller lease_wait / agent worker_start spans attach to the
        # same trace (best-effort causal attribution: the lease is reused
        # by later tasks, but THIS task paid the wait).
        trace_ctx = spec.get("trace_ctx") if tracing.enabled() else None
        lease_payload = {
            "resources": spec["resources"],
            "job_id": spec["job_id"],
            "submitter_node": self.node_id,
            "scheduling_strategy": strategy,
        }
        if trace_ctx:
            lease_payload["trace_ctx"] = trace_ctx
        resp = await self.controller.call("request_lease", lease_payload)
        if resp.get("status") != "ok":
            raise RuntimeError(f"lease request failed: {resp.get('status')}")
        agent_addr = tuple(resp["agent_addr"])
        agent = await self._client_for(agent_addr)
        worker_payload = {
            "resources": spec["resources"],
            "runtime_env": spec["runtime_env"],
            "job_id": spec["job_id"],
            "bundle": resp.get("bundle"),
        }
        if trace_ctx:
            worker_payload["trace_ctx"] = trace_ctx
        lease = await agent.call("lease_worker", worker_payload)
        if lease.get("status") != "ok":
            raise RuntimeError(
                f"worker lease failed: {lease.get('status')} {lease.get('error', '')}"
            )
        client = await self._client_for(tuple(lease["worker_addr"]))
        return LeasedWorker(
            lease["worker_id"],
            tuple(lease["worker_addr"]),
            client,
            lease["lease_id"],
            agent_addr,
            key,
        )

    async def _release_lease(self, worker: LeasedWorker, reusable: bool) -> None:
        # Always hand the lease back: the agent keeps the worker process warm
        # in its pool, so the next lease is cheap, and the node's resources
        # are never held hostage by an idle submitter (worker_pool.cc [N11]).
        # reusable=False tells the agent NOT to pool the worker (we saw its
        # connection die) — pooling it would burn the next lease's tasks.
        try:
            agent = await self._client_for(worker.agent_addr)
            await agent.call(
                "return_worker",
                {"lease_id": worker.lease_id, "reusable": reusable},
            )
        except Exception:  # rtlint: disable=swallowed-exception - agent gone: the lease died with it
            pass

    def _set_state_event(self, state: ObjectState) -> None:
        """Settle notification that is safe from ANY thread: on the io
        loop, set directly; from a caller thread, wake the loop only when
        someone actually parked on the event (state.waited) — an
        unconditional call_soon_threadsafe would cost one loop wakeup per
        task on the direct lane."""
        try:
            on_loop = asyncio.get_running_loop() is self.io.loop
        except RuntimeError:
            on_loop = False
        if on_loop:
            state.event.set()
        elif state.waited:
            try:
                self.io.loop.call_soon_threadsafe(state.event.set)
            except RuntimeError:
                pass  # loop already closed (shutdown)

    def _apply_reply(self, record: PendingTask, reply: dict) -> None:
        if reply.get("status") == "error":
            self._fail_returns_payload(record, reply["error"])
            return
        for rid, result in zip(record.return_ids, reply["returns"]):
            state = self._objects.get(rid)
            if state is None:
                continue
            if result["kind"] == "inline":
                state.status = INLINE
                state.data = result["data"]
                state.size = len(result["data"])
            else:
                state.status = SHM
                state.size = result["size"]
                state.locations = [result["location"]]
            state.record = None
            self._set_state_event(state)

    def _fail_returns(self, record: PendingTask, exc: Exception) -> None:
        payload, _ = serialization.serialize(exc)
        self._fail_returns_payload(record, payload)

    def _fail_returns_payload(self, record: PendingTask, error_payload) -> None:
        for rid in record.return_ids:
            state = self._objects.get(rid)
            if state is None:
                continue
            state.status = FAILED
            state.error = error_payload
            state.record = None
            self._set_state_event(state)

    async def _try_reconstruct(self, object_id: str) -> bool:
        """Object recovery via lineage re-execution ([N23]): reset the return
        states to PENDING and resubmit the creating task through the normal
        dispatch queue, then wait for it to finish."""
        record = self._lineage.get(object_id)
        if record is None or record.spec.get("actor_id"):
            return False
        fresh = PendingTask(record.spec, record.return_ids, [])
        states = []
        for rid in record.return_ids:
            state = ObjectState()
            self._objects[rid] = state
            states.append(state)
        self._enqueue_task(fresh)
        for state in states:
            await state.event.wait()
        state = self._objects.get(object_id)
        return state is not None and state.status in (INLINE, SHM)

    # ------------------------------------------------------------------
    # actor task submission (ordered, direct connection — N19 actor path)
    # ------------------------------------------------------------------
    def submit_actor_task(
        self,
        actor_id: str,
        method_name: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        max_task_retries: int = 0,
    ) -> list[ObjectRef]:
        task_id = self.next_task_id()
        if not args and not kwargs:
            payload, contained = serialization.EMPTY_ARGS_PAYLOAD, ()
        else:
            payload, contained = serialization.serialize((args, kwargs))
        arg_ref_ids = [r.id for r in contained]
        if arg_ref_ids:
            with self._refs_lock:
                for rid in arg_ref_ids:
                    self._submitted_refs[rid] = (
                        self._submitted_refs.get(rid, 0) + 1
                    )
        return_ids = [ObjectID.for_task_return(task_id, i) for i in range(num_returns)]
        tkey = (actor_id, method_name, num_returns, max_task_retries)
        template = self._actor_spec_templates.get(tkey)
        if template is None:
            template = self._actor_spec_templates[tkey] = {
                "task_id": "",
                "job_id": self.job_id,
                "actor_id": actor_id,
                "method": method_name,
                "name": f"{actor_id}.{method_name}",
                "args": b"",
                "num_returns": num_returns,
                "owner": {
                    "worker_id": self.worker_id,
                    "address": list(self.address),
                },
                "caller_id": self.worker_id,
                "seq": 0,  # assigned under the actor lock below
                "max_retries": max_task_retries,
                "retry_exceptions": False,
                "has_ref_args": False,
            }
        spec = dict(template)
        spec["task_id"] = task_id
        spec["args"] = payload
        spec["has_ref_args"] = bool(arg_ref_ids)
        traced = tracing.enabled()
        if traced:
            # begin/finish fast path (see submit_task): one span per actor
            # call on the submitting thread, closed right after creation —
            # the client-side cost of an actor submit is the seq+send step
            # below, which stays un-spanned to keep the actor lock short.
            submit_span = tracing.begin(
                f"submit {spec['name']}", task_id=task_id
            )
            spec["trace_ctx"] = {
                "trace_id": submit_span.trace_id,
                "span_id": submit_span.span_id,
            }
            tracing.finish(submit_span)
        record = PendingTask(spec, return_ids, arg_ref_ids)
        self._task_records[task_id] = record
        refs = []
        states = []
        for rid in return_ids:
            state = ObjectState()
            self._objects[rid] = state
            states.append(state)
            refs.append(self.new_object_ref(rid))
        # Seq assignment and the (possible) direct send are ONE atomic
        # step under the per-process actor lock: the wire then carries
        # frames in seq order — the C++ conn write queue is the ordered
        # actor queue (actor_task_submitter.cc send-in-order role).
        direct_client = None
        if (
            self._engine is not None
            and not arg_ref_ids
            and not traced
        ):
            direct_client = self._direct_actor_conn(actor_id)
        with self._actor_seq_lock:
            seq = self._actor_seq.get(actor_id, 0)
            self._actor_seq[actor_id] = seq + 1
            spec["seq"] = seq
            handle = 0
            if (
                direct_client is not None
                and self._actor_pending_slow.get(actor_id, 0) == 0
            ):
                # A pending slow send would write AFTER this frame and
                # invert program order — direct only when none are queued.
                engine = self._engine
                fl = self._fastlane
                if fl is not None:
                    ap = self._actor_spec_parts.get(tkey)
                    if ap is None:
                        ap = self._actor_spec_parts[tkey] = (
                            wire_gen.make_actor_task_spec_parts(template)
                        )
                    # One C call: splice (task_id, args), patch seq at its
                    # fixed offset, start the native call.
                    handle = fl.submit(
                        engine.handle, direct_client[0], b"push_actor_task",
                        ap[0], task_id, ap[1], payload, ap[2], seq, ap[3],
                        1 if self._direct_unsettled >= 2 else 0,
                    )
                else:
                    wire = wire_gen.encode_actor_task_spec(spec)
                    lib = (
                        engine.pylib
                        if len(wire) < engine._PYLIB_MAX_PAYLOAD
                        else engine.lib
                    )
                    starter = (
                        lib.rt_call_start_buf
                        if self._direct_unsettled >= 2
                        else lib.rt_call_start
                    )
                    handle = starter(
                        engine.handle, direct_client[0], b"push_actor_task",
                        15, wire, len(wire),
                    )
                if handle:
                    self._direct_unsettled += 1
                    # Keep the io-loop send gate in step so interleaved
                    # slow sends order correctly behind this frame.
                    gate = self._actor_send_gate.setdefault(
                        actor_id, {"next": 0, "waiters": {}}
                    )
                    gate["next"] = max(gate["next"], seq + 1)
                    if gate["waiters"]:
                        try:
                            self.io.loop.call_soon_threadsafe(
                                self._gate_release_waiters, actor_id
                            )
                        except RuntimeError:
                            pass
            if not handle:
                self._actor_pending_slow[actor_id] = (
                    self._actor_pending_slow.get(actor_id, 0) + 1
                )
        if handle:
            record.make_direct()
            record.attempts = 1
            record.native_handle = handle
            for state in states:
                state.record = record
            self._running_tasks[task_id] = direct_client[1]
            return refs
        self.io.spawn(self._run_actor_task(record))
        return refs

    def _direct_actor_conn(self, actor_id: str):
        """(conn_id, client) for an actor with a live direct connection,
        else None (first call to an actor always takes the asyncio path,
        which resolves the address and dials)."""
        addr = self._actor_addr_cache.get(actor_id)
        if addr is None:
            return None
        client = self._clients.get(tuple(addr))
        if client is None or not client.connected:
            return None
        conn_id = getattr(client, "_conn_id", None)
        if conn_id is None:
            return None
        return (conn_id, client)

    def _gate_release_waiters(self, actor_id: str) -> None:
        """io-loop: wake slow senders whose seq the direct lane passed."""
        gate = self._actor_send_gate.get(actor_id)
        if not gate:
            return
        for s, ev in list(gate["waiters"].items()):
            if s <= gate["next"]:
                ev.set()
                gate["waiters"].pop(s, None)

    async def _run_actor_task(self, record: PendingTask) -> None:
        spec = record.spec
        actor_id = spec["actor_id"]
        seq = spec["seq"]
        # In-order send gate: seq N may not write its push frame before
        # N-1 has written (or failed) — otherwise a caller racing actor
        # startup can have seq 2 observe ALIVE first and baseline the
        # receiver's expected counter past 0/1. Replies are NOT serialized:
        # the gate opens from the client's on_sent hook, so later calls
        # pipeline behind the write, not behind the round-trip.
        gate = self._actor_send_gate.setdefault(
            actor_id, {"next": 0, "waiters": {}}
        )
        while gate["next"] < seq:
            event = gate["waiters"].setdefault(seq, asyncio.Event())
            await event.wait()
        released = False

        def _release_gate() -> None:
            nonlocal released
            if released:
                return
            released = True
            gate["next"] = max(gate["next"], seq + 1)
            waiter = gate["waiters"].pop(gate["next"], None)
            if waiter is not None:
                waiter.set()
            if not record.direct:
                # Slow-path submits counted themselves in pending_slow to
                # keep the direct lane from jumping program order; the
                # frame is now on the wire (or abandoned) — release.
                with self._actor_seq_lock:
                    self._actor_pending_slow[actor_id] = max(
                        0, self._actor_pending_slow.get(actor_id, 1) - 1
                    )

        attempts = 0
        try:
            while True:
                attempts += 1
                try:
                    if record.done or spec["task_id"] in self._cancelled_tasks:
                        # cancelled while waiting for the actor to come up;
                        # cancel() already failed the returns.
                        return
                    client = await self._actor_client(actor_id)
                    self._running_tasks[spec["task_id"]] = client
                    try:
                        reply = await client.call(
                            "push_actor_task", spec, on_sent=_release_gate
                        )
                    finally:
                        self._running_tasks.pop(spec["task_id"], None)
                    if reply.get("status") == "cancelled":
                        self._fail_returns(
                            record,
                            exceptions.TaskCancelledError(
                                f"actor task {spec['name']} was cancelled"
                            ),
                        )
                        return
                    if record.done:
                        return  # cancel() finished the record while in flight
                    self._apply_reply(record, reply)
                    return
                except exceptions.ActorUnavailableError:
                    self._fail_returns(
                        record, exceptions.ActorUnavailableError(actor_id)
                    )
                    return
                except (ConnectionLost, RpcError, OSError):
                    # Actor possibly dead/restarting: consult the controller.
                    self._actor_addr_cache.pop(actor_id, None)
                    info = await self.controller.call(
                        "get_actor_info", {"actor_id": actor_id}
                    )
                    state = info.get("state")
                    # In-flight calls when an actor dies fail immediately
                    # unless max_task_retries allows a retry on the restarted
                    # incarnation (reference actor_task_submitter.cc policy).
                    if attempts <= spec["max_retries"]:
                        if state in ("RESTARTING", "PENDING", "ALIVE"):
                            await asyncio.sleep(0.2)
                            continue
                    exc: Exception
                    if state in ("RESTARTING", "PENDING"):
                        exc = exceptions.ActorUnavailableError(
                            f"actor {actor_id} is {state} during {spec['method']}"
                            " (set max_task_retries to retry across restarts)"
                        )
                    else:
                        cause = info.get("death_cause")
                        exc = exceptions.ActorDiedError(
                            f"actor {actor_id} died (state={state}"
                            + (f", cause: {cause}" if cause else "")
                            + f") during {spec['method']}"
                        )
                    self._fail_returns(record, exc)
                    return
        finally:
            # A task that never reached the wire (cancelled, actor dead,
            # address resolution failed) must still open the gate or every
            # later seq to this actor deadlocks behind it.
            _release_gate()
            # Settle the record: actor tasks bypass _finish_record (their
            # arg-ref release lives below), so without this every actor
            # call leaked a PendingTask in _task_records for the driver's
            # lifetime (observed: hundreds of undone records per module).
            record.done = True
            self._task_records.pop(spec["task_id"], None)
            self._cancelled_tasks.discard(spec["task_id"])
            if record.done_event is not None:
                record.done_event.set()
            with self._refs_lock:
                for rid in record.arg_refs:
                    count = self._submitted_refs.get(rid, 0) - 1
                    if count <= 0:
                        self._submitted_refs.pop(rid, None)
                    else:
                        self._submitted_refs[rid] = count
            for rid in record.arg_refs:
                self._maybe_free(rid)

    async def _actor_client(self, actor_id: str) -> RpcClient:
        addr = self._actor_addr_cache.get(actor_id)
        if addr is None:
            info = await self.controller.call("get_actor_info", {"actor_id": actor_id})
            deadline = time.monotonic() + global_config().actor_ready_timeout_s
            while info.get("state") in ("PENDING", "RESTARTING"):
                if time.monotonic() > deadline:
                    raise exceptions.ActorUnavailableError(
                        f"actor {actor_id} still {info.get('state')} after "
                        f"{global_config().actor_ready_timeout_s:.0f}s"
                    )
                await asyncio.sleep(0.1)
                info = await self.controller.call(
                    "get_actor_info", {"actor_id": actor_id}
                )
            if info.get("state") != "ALIVE":
                raise ConnectionLost(f"actor {actor_id} state={info.get('state')}")
            addr = tuple(info["address"])
            self._actor_addr_cache[actor_id] = addr
        return await self._client_for(addr)

    # ------------------------------------------------------------------
    # owner-protocol RPC handlers (served to other processes)
    # ------------------------------------------------------------------
    async def rpc_get_object(self, conn, payload) -> dict:
        object_id = payload["object_id"]
        state = self._objects.get(object_id)
        if state is None:
            return {"status": "failed", "error": serialization.serialize(
                exceptions.ObjectLostError(f"{object_id}: unknown to owner")
            )[0]}
        await self._await_state(state)
        if state.status == FAILED:
            return {"status": "failed", "error": state.error}
        if state.status == INLINE:
            return {"status": "inline", "data": state.data}
        return {"status": "shm", "locations": state.locations, "size": state.size}

    async def rpc_wait_object(self, conn, payload) -> dict:
        state = self._objects.get(payload["object_id"])
        if state is not None:
            await self._await_state(state)
        return {"status": "ok"}

    async def rpc_add_borrower(self, conn, payload) -> dict:
        self._borrowers.setdefault(payload["object_id"], set()).add(payload["borrower"])
        return {"status": "ok"}

    async def rpc_remove_borrower(self, conn, payload) -> dict:
        borrowers = self._borrowers.get(payload["object_id"])
        if borrowers is not None:
            borrowers.discard(payload["borrower"])
            if not borrowers:
                self._borrowers.pop(payload["object_id"], None)
                self._maybe_free(payload["object_id"])
        return {"status": "ok"}

    async def rpc_add_location(self, conn, payload) -> dict:
        state = self._objects.get(payload["object_id"])
        if state is not None:
            state.locations.append(payload["location"])
        return {"status": "ok"}

    async def rpc_ping(self, conn, payload) -> dict:
        return {"status": "ok", "worker_id": self.worker_id}


def _release_pinned(store: ObjectStoreClient, object_id: str) -> None:
    try:
        store.unpin(object_id)
    except Exception:  # rtlint: disable=swallowed-exception - unpin of an object the store may have dropped
        pass


def _encode_strategy(strategy: Any) -> dict:
    """Normalize a scheduling strategy object to a wire dict."""
    if strategy is None:
        return {}
    if isinstance(strategy, str):
        return {"kind": strategy}  # "SPREAD" | "DEFAULT"
    if isinstance(strategy, dict):
        return strategy
    # PlacementGroupSchedulingStrategy / NodeAffinitySchedulingStrategy
    kind = type(strategy).__name__
    if kind == "PlacementGroupSchedulingStrategy":
        return {
            "kind": "pg",
            "pg_id": strategy.placement_group.id,
            "bundle_index": strategy.placement_group_bundle_index,
            "capture_child_tasks": getattr(
                strategy, "placement_group_capture_child_tasks", False
            ),
        }
    if kind == "NodeAffinitySchedulingStrategy":
        return {"kind": "node_affinity", "node_id": strategy.node_id, "soft": strategy.soft}
    raise ValueError(f"unknown scheduling strategy: {strategy!r}")

"""Serialization for task args/returns and ray_tpu.put values.

Role-equivalent of the reference's SerializationContext
(python/ray/_private/serialization.py): cloudpickle for code/closures,
pickle protocol 5 with out-of-band buffers so large numpy/jax host arrays are
written as raw bytes (and reconstructed zero-copy as views onto the
shared-memory arena on the read side).

Wire layout of a serialized value:
    [u32 nbufs][u64 len_meta][meta pickle][u64 len_buf0][buf0]...
ObjectRefs inside values are replaced at pickle time by _RefPlaceholder and
collected, so the runtime can (a) register borrows with owners and (b)
resolve them back to live ObjectRefs on the consumer side.
"""

from __future__ import annotations

import io
import os
import pickle
import struct
import sys
import sysconfig
import types
from typing import Any, Callable

import cloudpickle

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

# Directories whose modules are importable on every worker (stdlib +
# site-packages + this framework). Functions/classes from any OTHER module
# (user scripts, pytest files) are registered for pickle-by-value — workers
# must not need the driver's sys.path to unpickle user code. The reference
# only gets this for __main__; we extend it to all non-installed modules.
_INSTALLED_ROOTS = tuple(
    os.path.realpath(p)
    for p in {
        sysconfig.get_paths().get("stdlib", ""),
        sysconfig.get_paths().get("purelib", ""),
        sysconfig.get_paths().get("platlib", ""),
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    }
    if p
)
_byvalue_checked: set[str] = set()


def _maybe_register_by_value(obj: Any) -> None:
    mod_name = getattr(obj, "__module__", None)
    if not mod_name or mod_name in _byvalue_checked:
        return
    _byvalue_checked.add(mod_name)
    if mod_name == "__main__" or mod_name.partition(".")[0] in sys.builtin_module_names:
        return
    module = sys.modules.get(mod_name)
    mod_file = getattr(module, "__file__", None)
    if module is None or not mod_file:
        return
    real = os.path.realpath(mod_file)
    if any(real.startswith(root + os.sep) for root in _INSTALLED_ROOTS):
        return
    try:
        cloudpickle.register_pickle_by_value(module)
    except Exception:  # rtlint: disable=swallowed-exception - module rejects by-value: fall back to by-reference
        pass


class _RefPlaceholder:
    __slots__ = ("object_id", "owner_address")

    def __init__(self, object_id: str, owner_address: tuple | None):
        self.object_id = object_id
        self.owner_address = owner_address

    def __reduce__(self):
        return (_RefPlaceholder, (self.object_id, self.owner_address))


class _Pickler(cloudpickle.CloudPickler):
    def __init__(self, file, collected_refs: list, protocol: int = 5, **kw):
        super().__init__(file, protocol=protocol, **kw)
        self._collected_refs = collected_refs

    def persistent_id(self, obj: Any):
        from ray_tpu._private.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            self._collected_refs.append(obj)
            return ("raytpu_ref", obj.id, obj.owner_address)
        if isinstance(obj, (types.FunctionType, type)):
            _maybe_register_by_value(obj)
        return None


class _Unpickler(pickle.Unpickler):
    def __init__(self, file, ref_resolver, buffers=None):
        super().__init__(file, buffers=buffers)
        self._ref_resolver = ref_resolver

    def persistent_load(self, pid):
        tag, object_id, owner_address = pid
        if tag != "raytpu_ref":
            raise pickle.UnpicklingError(f"unknown persistent id {tag!r}")
        if self._ref_resolver is None:
            raise pickle.UnpicklingError("ObjectRef found but no resolver given")
        return self._ref_resolver(object_id, owner_address)


# Fast lane for plain-data values (the typical task args/returns on the
# hot path): the stock C pickler is ~10x cheaper than instantiating a
# CloudPickler per call, and such values can contain no ObjectRefs,
# functions, or out-of-band buffers by construction. The high bit of the
# nbufs header marks these payloads so deserialize can use the stock C
# unpickler (persistent ids are impossible in them).
_PLAIN_FLAG = 0x80000000
_PLAIN_SCALARS = frozenset((type(None), bool, int, float, str, bytes))


def _is_plain(value: Any, depth: int = 4) -> bool:
    t = type(value)
    if t in _PLAIN_SCALARS:
        return True
    if depth <= 0:
        return False
    if t is tuple or t is list:
        return len(value) <= 16 and all(
            _is_plain(item, depth - 1) for item in value
        )
    if t is dict:
        return len(value) <= 16 and all(
            type(k) is str and _is_plain(v, depth - 1)
            for k, v in value.items()
        )
    return False


def serialize_parts(value: Any) -> tuple[list, int, list]:
    """Serialize without joining: returns (parts, total_nbytes,
    contained_object_refs) where parts is a list of bytes/memoryview in wire
    order. The put path streams parts straight into its shared-memory
    allocation — one copy total, instead of join-then-copy (the join of an
    8 MiB array costs as much as the final memcpy itself)."""
    if _is_plain(value):
        meta = pickle.dumps(value, protocol=5)
        return (
            [_U32.pack(_PLAIN_FLAG), _U64.pack(len(meta)), meta],
            12 + len(meta),
            [],
        )
    buffers: list[pickle.PickleBuffer] = []
    refs: list = []
    meta_io = io.BytesIO()
    pickler = _Pickler(meta_io, refs, protocol=5, buffer_callback=buffers.append)
    pickler.dump(value)
    meta = meta_io.getbuffer()

    parts: list = [_U32.pack(len(buffers)), _U64.pack(meta.nbytes), meta]
    total = 12 + meta.nbytes
    for buffer in buffers:
        raw = buffer.raw()
        parts.append(_U64.pack(raw.nbytes))
        parts.append(raw)
        total += 8 + raw.nbytes
    return parts, total, refs


def join_parts(parts) -> bytes:
    """Wire-order parts -> one contiguous payload."""
    return b"".join(
        bytes(p) if isinstance(p, memoryview) else p for p in parts
    )


def serialize(value: Any) -> tuple[bytes, list]:
    """Returns (payload, contained_object_refs)."""
    parts, _total, refs = serialize_parts(value)
    return join_parts(parts), refs


def serialized_size(payload: bytes) -> int:
    return len(payload)


def deserialize(
    payload: bytes | memoryview,
    ref_resolver: Callable[[str, Any], Any] | None = None,
    zero_copy: bool = True,
) -> Any:
    view = memoryview(payload)
    (nbufs,) = _U32.unpack_from(view, 0)
    (meta_len,) = _U64.unpack_from(view, 4)
    pos = 12
    meta = view[pos : pos + meta_len]
    pos += meta_len
    if nbufs & _PLAIN_FLAG:
        # Plain-data payload: stock C unpickler, nothing persistent inside.
        return pickle.loads(meta)
    buffers = []
    for _ in range(nbufs):
        (blen,) = _U64.unpack_from(view, pos)
        pos += 8
        buf = view[pos : pos + blen]
        # zero_copy=False makes an owning copy (needed if the arena slice is
        # released after get, e.g. values that outlive the store mapping).
        buffers.append(buf if zero_copy else bytes(buf))
        pos += blen
    unpickler = _Unpickler(io.BytesIO(bytes(meta)), ref_resolver, buffers)
    return unpickler.load()


def dumps_function(fn: Any) -> bytes:
    # Run through _Pickler (not bare cloudpickle.dumps) so persistent_id
    # fires for every NESTED function/class too — a task fn calling a helper
    # from a sibling user module must ship that module by value as well.
    out = io.BytesIO()
    _Pickler(out, [], protocol=5).dump(fn)
    return out.getvalue()


def loads_function(raw: bytes, ref_resolver: Callable | None = None) -> Any:
    return _Unpickler(io.BytesIO(raw), ref_resolver).load()


# Precomputed payloads for the two dominant hot-path values: a no-arg
# call's ((), {}) and a None return. Serializing them is pure fixed cost
# (~8us of pickler setup per task on the microbenchmark's noop loop).
EMPTY_ARGS_PAYLOAD: bytes = serialize(((), {}))[0]
NONE_PAYLOAD: bytes = serialize(None)[0]

"""Driver/worker global runtime state and the implementation of the
top-level API (init/shutdown/get/put/wait/kill/...).

Role-equivalent of python/ray/_private/worker.py in the reference
(:: init, connect, get, put, wait, Worker global state, log listeners).
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from typing import Any, Sequence

from ray_tpu import exceptions
from ray_tpu._private import serialization
from ray_tpu._private.config import global_config, reset_config
from ray_tpu._private.core_context import CoreContext
from ray_tpu._private.ids import JobID
from ray_tpu._private.node import LocalCluster
from ray_tpu._private.object_ref import ObjectRef

_global_ctx: CoreContext | None = None
_local_cluster: LocalCluster | None = None
_autoscaler_monitor = None  # AutoscalerMonitor when init(autoscaling=...)
_is_driver = False
_lock = threading.RLock()
_runtime_context_extras: dict = {}


def set_global_context(ctx: CoreContext, is_driver: bool) -> None:
    global _global_ctx, _is_driver
    _global_ctx = ctx
    _is_driver = is_driver


def get_global_context() -> CoreContext:
    if _global_ctx is None:
        raise RuntimeError(
            "ray_tpu has not been initialized; call ray_tpu.init() first"
        )
    return _global_ctx


def is_initialized() -> bool:
    return _global_ctx is not None


def init(
    address: str | None = None,
    *,
    num_cpus: int | None = None,
    resources: dict | None = None,
    object_store_memory: int | None = None,
    log_to_driver: bool = True,
    namespace: str = "default",
    runtime_env: dict | None = None,
    autoscaling: "str | dict | None" = None,
    _system_config: dict | None = None,
    ignore_reinit_error: bool = False,
) -> dict:
    """Start (or connect to) a cluster and connect this process as driver.

    Like the reference's ray.init(): no address starts a local head
    (controller + node agent subprocesses + shm store); ``address`` of the
    form "host:port" (controller) connects to an existing cluster.
    Resources are *assertions* (resource lying is supported for tests, see
    SURVEY §4.4.3): pass ``resources={"TPU": 8}`` on a laptop and the
    scheduler will believe you.
    """
    global _local_cluster
    with _lock:
        if _global_ctx is not None:
            if ignore_reinit_error:
                return runtime_info()
            raise RuntimeError("ray_tpu.init() called twice")
        global_config().apply_system_config(_system_config)

        job_id = JobID.random()
        if address == "auto":
            # Reference's ray.init("auto"): resolve from the environment
            # (set for job-submission drivers and `ray_tpu start` shells).
            address = os.environ.get("RAYTPU_ADDRESS")
            if not address:
                raise ConnectionError(
                    'init("auto") needs RAYTPU_ADDRESS in the environment'
                )
        if address is None:
            custom = dict(resources or {})
            if num_cpus is not None:
                custom["CPU"] = num_cpus
            cluster = LocalCluster()
            cluster.start_head(
                resources=custom,
                store_capacity=object_store_memory or 0,
            )
            _local_cluster = cluster
            # Driver-side tracing/profile exports land in the session dir
            # (workers inherit it via RAYTPU_SESSION_DIR at spawn).
            os.environ["RAYTPU_SESSION_DIR"] = cluster.session_dir
            from ray_tpu.util import tracing as _tracing

            _tracing.configure(cluster.session_dir)
            controller_addr = cluster.controller_addr
            agent_addr = cluster.head_agent_addr
            store_info = cluster.head_store_info
            node_id = cluster.head_node_id
        else:
            host, port = address.rsplit(":", 1)
            controller_addr = (host, int(port))
            agent_addr, store_info, node_id = _discover_local_node(controller_addr)

        ctx = CoreContext(
            job_id=job_id,
            node_id=node_id,
            controller_addr=controller_addr,
            agent_addr=agent_addr,
            store_info=store_info,
            is_driver=True,
        )
        ctx.connect()
        set_global_context(ctx, is_driver=True)
        _runtime_context_extras["namespace"] = namespace
        _runtime_context_extras["runtime_env"] = runtime_env or {}
        if log_to_driver:
            _subscribe_logs(ctx, job_id)
        atexit.register(shutdown)
        if autoscaling is not None:
            # Bootstrap-launched monitor (autoscaler/_private/monitor.py
            # role): the cluster autoscales with NO user-side autoscaler
            # construction. "v2"/"v1" or a dict of monitor kwargs. A bad
            # config must not leak the just-started cluster processes.
            global _autoscaler_monitor
            from ray_tpu.autoscaler.monitor import start_monitor_from_config

            try:
                _autoscaler_monitor = start_monitor_from_config(
                    autoscaling, local_cluster=_local_cluster
                )
            except Exception:
                shutdown()  # RLock: safe to re-enter from init's lock
                raise
        return runtime_info()


def _discover_local_node(controller_addr: tuple) -> tuple:
    """Connect-to-existing: pick an agent (prefer one on this host)."""
    from ray_tpu._private.rpc import RpcClient

    probe = CoreContextProbe(controller_addr)
    nodes = probe.call("list_nodes", {})
    probe.close()
    alive = [n for n in nodes if n["alive"]]
    if not alive:
        raise RuntimeError("no alive nodes in cluster")
    node = alive[0]
    return tuple(node["agent_addr"]), node["store_info"], node["node_id"]


class CoreContextProbe:
    """Minimal one-shot RPC helper usable before the main context exists."""

    def __init__(self, addr: tuple):
        from ray_tpu._private.rpc import IoThread, RpcClient

        self.io = IoThread("probe-io")
        self.client = RpcClient(tuple(addr), name="probe")
        self.io.run(self.client.connect())

    def call(self, method: str, payload: Any, timeout: float | None = 30) -> Any:
        return self.io.run(self.client.call(method, payload), timeout)

    def close(self) -> None:
        try:
            self.io.run(self.client.close())
        except Exception:  # rtlint: disable=swallowed-exception - close of a dead controller conn at shutdown
            pass
        self.io.stop()


def _subscribe_logs(ctx: CoreContext, job_id: str) -> None:
    """Print worker stdout/stderr with (pid=) prefixes, like the reference's
    log monitor → driver pipeline."""

    def on_log(message):
        if message.get("job_id") not in ("", job_id):
            return
        stream = sys.stderr if message.get("kind") == "err" else sys.stdout
        print(f"(pid={message.get('pid')}) {message.get('line')}", file=stream)

    ctx.controller.on_push("logs", on_log)
    ctx.io.run(ctx.subscribe_channels(["logs", "error"]))


def shutdown() -> None:
    global _global_ctx, _local_cluster, _autoscaler_monitor
    with _lock:
        if _autoscaler_monitor is not None:
            try:
                _autoscaler_monitor.stop()
            except Exception:  # rtlint: disable=swallowed-exception - monitor already stopped
                pass
            _autoscaler_monitor = None
        if _global_ctx is not None:
            try:
                # Compiled DAGs hold resident worker loops and ring slots
                # — tear them down while the RPC plane is still up.
                from ray_tpu.dag import dag as dag_mod

                dag_mod.shutdown_all()
            except Exception:  # rtlint: disable=swallowed-exception - shutdown must not be blocked by a wedged graph
                pass
            _global_ctx.shutdown()
            _global_ctx = None
        if _local_cluster is not None:
            _local_cluster.shutdown()
            _local_cluster = None


def runtime_info() -> dict:
    ctx = get_global_context()
    return {
        "job_id": ctx.job_id,
        "node_id": ctx.node_id,
        "controller_address": f"{ctx.controller_addr[0]}:{ctx.controller_addr[1]}",
        "session_dir": (
            _local_cluster.session_dir if _local_cluster is not None else None
        ),
    }


# ---------------------------------------------------------------------------
# public API implementations
# ---------------------------------------------------------------------------
def put(value: Any) -> ObjectRef:
    return get_global_context().put(value)


def get(refs, timeout: float | None = None):
    return get_global_context().get(refs, timeout=timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: float | None = None,
    fetch_local: bool = True,
):
    return get_global_context().wait(
        refs, num_returns=num_returns, timeout=timeout, fetch_local=fetch_local
    )


def kill(actor, *, no_restart: bool = True) -> None:
    ctx = get_global_context()
    ctx.io.run(
        ctx.controller.call(
            "kill_actor",
            {"actor_id": actor._actor_id, "no_restart": no_restart},
        )
    )


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    """Cancel the task that creates ``ref`` (reference: ray.cancel /
    test_cancel.py semantics). Queued tasks fail with TaskCancelledError;
    running tasks get KeyboardInterrupt (force=False) or their worker
    SIGKILLed (force=True -> WorkerCrashedError); finished tasks no-op."""
    get_global_context().cancel(ref, force=force)


def nodes() -> list[dict]:
    ctx = get_global_context()
    return ctx.io.run(ctx.controller.call("list_nodes", {}))


def cluster_resources() -> dict:
    ctx = get_global_context()
    return ctx.io.run(ctx.controller.call("cluster_resources", {}))


def available_resources() -> dict:
    ctx = get_global_context()
    return ctx.io.run(ctx.controller.call("available_resources", {}))


def timeline(filename: str | None = None) -> dict:
    """Chrome-trace JSON (Trace Event Format) for the whole session —
    spans, task events, and counter snapshots merged onto per-process
    tracks; loads directly in Perfetto / chrome://tracing."""
    from ray_tpu.util.timeline import build_chrome_trace

    ctx = get_global_context()
    events = ctx.io.run(
        ctx.controller.call("list_task_events", {"limit": 100_000})
    )
    session_dir = (
        _local_cluster.session_dir
        if _local_cluster is not None
        else os.environ.get("RAYTPU_SESSION_DIR", "")
    )
    trace = build_chrome_trace(session_dir, task_events=events)
    if filename:
        from ray_tpu._private.atomic_io import atomic_write_json

        atomic_write_json(filename, trace)
    return trace

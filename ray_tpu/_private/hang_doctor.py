"""Cluster-wide hang report builder (ISSUE 14).

The comm watchdog (:mod:`ray_tpu.util.collective.flight`) only knows its
own process: "my recv on ``train:recv:s{}f{}v{}`` has aged past the
channel deadline". Attribution needs the other side of every wire, so on
a ``comm_stall`` event the controller harvests each node agent
(``comm_evidence`` → per-worker ``comm_flight`` RPC: last-N ring
records, in-flight summary, native stack dump) and hands the pile to
:func:`build_report`, which merges it into one answer:

    for each stalled channel, which ranks are *waiting* at the sequence
    frontier, which ranks are *missing* from it (no in-flight record and
    a completed-seq high-water mark behind the cluster's), and which
    ranks the waiters' wire records actually point at.

The missing set is the laggard signal: a rank wedged (or chaos-delayed)
*before* its op reaches the recorder simply has no record at the
frontier ``(group, tag, seq)`` while every peer's record ages there.

Each runtime p2p channel is also reconciled against the PR-12 static
commgraph: a ``send``/``recv`` channel whose tag skeleton unifies with
no certified static site is flagged as *protocol drift* — traffic the
static verifier never saw, i.e. code bypassing the blessed wire idiom or
a schedule desync manufacturing tags outside the certified family.
Collective and overlap kinds are exempt (their default tags are
recorder-synthesized, not call-site literals).
"""

from __future__ import annotations

import ast
import os
import threading
import time
from typing import Any, Iterable, Optional

INFLIGHT_STATES = ("enqueued", "launched")

# Runtime record kinds that map onto static commgraph site kinds.
_P2P_KINDS = ("send", "recv")


# ---------------------------------------------------------------------------
# static-graph reconciliation (best-effort, cached)
# ---------------------------------------------------------------------------

_static_lock = threading.Lock()
_static_cache: Optional[list[dict]] = None


def static_comm_sites(root: Optional[str] = None) -> list[dict]:
    """The repo's static comm sites (send/recv/collective tag skeletons),
    extracted once per process by walking the installed ``ray_tpu``
    package with the rtgraph extractor. Best-effort: returns ``[]`` on
    any failure or when ``RAY_TPU_HANG_STATIC_RECONCILE=0`` — drift
    checking then degrades to "unknown", never to a false positive."""
    global _static_cache
    if os.environ.get("RAY_TPU_HANG_STATIC_RECONCILE", "1") == "0":
        return []
    with _static_lock:
        if _static_cache is not None:
            return _static_cache
        sites: list[dict] = []
        try:
            from ray_tpu.devtools.analysis import commgraph

            if root is None:
                import ray_tpu

                root = os.path.dirname(os.path.abspath(ray_tpu.__file__))
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [
                    d for d in dirnames
                    if not d.startswith((".", "__pycache__"))
                ]
                for name in sorted(filenames):
                    if not name.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, name)
                    try:
                        with open(path, encoding="utf-8") as f:
                            tree = ast.parse(f.read())
                        for site in commgraph.extract_sites(tree, path):
                            sites.append(site)
                    except Exception:  # rtlint: disable=swallowed-exception - one unparseable file must not kill reconciliation
                        continue
        except Exception:  # rtlint: disable=swallowed-exception - devtools absent or unreadable tree: drift check degrades to unknown
            sites = []
        _static_cache = sites
        return sites


def _reset_static_cache() -> None:
    """Tests only."""
    global _static_cache
    with _static_lock:
        _static_cache = None


def channel_in_static_graph(
    kind: str, tag_skeleton: str, sites: Iterable[dict]
) -> Optional[bool]:
    """True/False when the static graph can answer, None when it can't
    (no sites harvested, or a kind the static graph doesn't certify)."""
    if kind not in _P2P_KINDS:
        return None
    sites = [s for s in sites if s.get("kind") in _P2P_KINDS]
    if not sites:
        return None
    try:
        from ray_tpu.devtools.analysis import commgraph

        runtime = commgraph.parse_skeleton(tag_skeleton)
        for s in sites:
            static = commgraph.parse_skeleton(s.get("tag", ""))
            if commgraph.skeletons_unify(static, runtime):
                return True
        return False
    except Exception:  # rtlint: disable=swallowed-exception - reconciliation is advisory; report still names ranks
        return None


# ---------------------------------------------------------------------------
# evidence merge
# ---------------------------------------------------------------------------

def _iter_worker_evidence(evidence: dict) -> Iterable[tuple[str, str, dict]]:
    """Yield (node_id, worker_id, worker payload) over a harvest result
    shaped {node_id: {"workers": {worker_id: payload}}}."""
    for node_id, node_res in (evidence or {}).items():
        if not isinstance(node_res, dict):
            continue
        for wid, wres in (node_res.get("workers") or {}).items():
            if isinstance(wres, dict) and wres.get("status") == "ok":
                yield node_id, wid, wres


def _merge_channel(channel: str, records: list[dict]) -> dict:
    """Fold every rank's records on one channel into the who-is-missing
    verdict. ``records`` carry a ``_worker``/``_node`` annotation."""
    world = max((int(r.get("world_size") or 1) for r in records), default=1)
    inflight = [r for r in records if r.get("state") in INFLIGHT_STATES]
    done_seq: dict[int, int] = {}
    rank_worker: dict[int, str] = {}
    for r in records:
        rank = int(r.get("rank", 0))
        rank_worker.setdefault(rank, r.get("_worker", "?"))
        if r.get("state") == "completed":
            seq = int(r.get("seq") or 0)
            if seq > done_seq.get(rank, -1):
                done_seq[rank] = seq
    frontier = max(
        (int(r.get("seq") or 0) for r in inflight),
        default=max(done_seq.values(), default=0),
    )
    waiting = []
    waited_on: set[int] = set()
    for r in sorted(inflight, key=lambda r: -float(r.get("age_s") or 0.0)):
        rank = int(r.get("rank", 0))
        peer = int(r.get("peer", -1))
        if peer >= 0:
            waited_on.add(peer)
        waiting.append({
            "rank": rank,
            "seq": int(r.get("seq") or 0),
            "age_s": float(r.get("age_s") or 0.0),
            "peer": peer,
            "state": r.get("state"),
            "site": r.get("site"),
            "trace_id": r.get("trace_id"),
            "worker": r.get("_worker"),
            "node": r.get("_node"),
        })
    waiting_ranks = {w["rank"] for w in waiting}
    missing = sorted(
        rank for rank in range(world)
        if rank not in waiting_ranks
        and done_seq.get(rank, -1) < frontier
    )
    # A rank a waiter's wire record explicitly points at is a suspect
    # even if its own evidence never arrived (dead process, lost node).
    suspects = sorted(set(missing) | (waited_on - waiting_ranks))
    sample = records[-1]
    return {
        "channel": channel,
        "group": sample.get("group"),
        "kind": sample.get("kind"),
        "tag_skeleton": channel.rsplit(":", 1)[-1],
        "world_size": world,
        "frontier_seq": frontier,
        "waiting_ranks": waiting,
        "missing_ranks": missing,
        "suspect_ranks": suspects,
        "last_completed_seq_by_rank": {
            str(k): v for k, v in sorted(done_seq.items())
        },
        "rank_worker": {str(k): v for k, v in sorted(rank_worker.items())},
    }


def build_report(
    stalls: list[dict],
    evidence: dict,
    static_sites: Optional[list[dict]] = None,
    include_stacks: bool = True,
) -> dict:
    """Merge watchdog stall events + the cluster evidence harvest into
    one hang report. Pure on its inputs (deterministic, unit-testable);
    ``static_sites=None`` means "harvest them yourself, best-effort"."""
    if static_sites is None:
        static_sites = static_comm_sites()

    records: list[dict] = []
    stacks: dict[str, Any] = {}
    nodes: set[str] = set()
    for node_id, wid, wres in _iter_worker_evidence(evidence):
        nodes.add(node_id)
        for r in wres.get("records") or []:
            r = dict(r)
            r["_worker"] = wid
            r["_node"] = node_id
            records.append(r)
        if include_stacks and wres.get("stacks"):
            stacks[wid] = {
                "node": node_id,
                "pid": wres.get("pid"),
                "current_task": wres.get("current_task"),
                "stacks": wres.get("stacks"),
                "asyncio_tasks": wres.get("asyncio_tasks", {}),
            }

    # Channels to diagnose: every channel a watchdog flagged, plus any
    # channel whose harvested records are themselves marked stalled.
    flagged = {s.get("channel") for s in stalls if s.get("channel")}
    flagged |= {
        r.get("channel") for r in records
        if r.get("stalled") and r.get("channel")
    }
    by_channel: dict[str, list[dict]] = {}
    for r in records:
        ch = r.get("channel")
        if ch in flagged:
            by_channel.setdefault(ch, []).append(r)

    channels = []
    for ch in sorted(flagged):
        recs = by_channel.get(ch)
        if not recs:
            continue
        merged = _merge_channel(ch, recs)
        merged["in_static_graph"] = channel_in_static_graph(
            merged["kind"], merged["tag_skeleton"], static_sites
        )
        merged["protocol_drift"] = merged["in_static_graph"] is False
        channels.append(merged)
    # Most suspects first: the channel pinning the most blame leads.
    channels.sort(key=lambda c: -len(c["suspect_ranks"]))

    lines = []
    for c in channels:
        who = ", ".join(f"rank {r}" for r in c["suspect_ranks"]) or "nobody"
        lines.append(
            f"{c['channel']} seq {c['frontier_seq']}: "
            f"{len(c['waiting_ranks'])}/{c['world_size']} ranks waiting, "
            f"suspect {who}"
            + (" [PROTOCOL DRIFT: channel absent from static commgraph]"
               if c["protocol_drift"] else "")
        )
    return {
        "generated_at": time.time(),
        "stall_events": list(stalls),
        "channels": channels,
        "nodes": sorted(nodes),
        "workers_reporting": len(stacks) or len({
            wid for _, wid, _ in _iter_worker_evidence(evidence)
        }),
        "stacks": stacks,
        "summary": lines,
    }


def blamed_ranks(report: dict) -> set:
    """Every rank a merged hang report points a finger at — the union of
    suspect and missing ranks across all diagnosed channels. The rtdag
    supervisor records this next to its own victim ranks so post-mortems
    can check the two diagnosis planes (controller liveness vs comm
    evidence) named the same culprit."""
    blamed: set = set()
    for ch in (report or {}).get("channels") or []:
        blamed.update(ch.get("suspect_ranks") or [])
        blamed.update(ch.get("missing_ranks") or [])
    return blamed

"""Core-runtime microbenchmarks.

Role-equivalent of python/ray/_private/ray_perf.py (`ray microbenchmark`,
SURVEY §4.5/§6): single-client sync tasks/s, 1:N async tasks/s, actor
calls/s, put/get throughput. Prints one line per benchmark; used by the
release-style perf suite to track core-runtime regressions.
"""

from __future__ import annotations

import time

import numpy as np


def _rate(n: int, seconds: float) -> str:
    return f"{n / seconds:,.0f}/s"


def main() -> dict:
    import ray_tpu

    owns_cluster = not ray_tpu.is_initialized()
    if owns_cluster:
        ray_tpu.init(num_cpus=8)
    results: dict[str, float] = {}

    @ray_tpu.remote
    def noop():
        return None

    @ray_tpu.remote
    class Actor:
        def noop(self):
            return None

    # Warmup: spawn workers + ship code, then let the spawn burst settle —
    # the sync phase must not time worker-startup noise (the reference's
    # `ray microbenchmark` warms up each phase the same way).
    ray_tpu.get([noop.remote() for _ in range(20)])
    time.sleep(1.0)
    for _ in range(20):
        ray_tpu.get(noop.remote())

    n = 200
    start = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(noop.remote())
    dt = time.perf_counter() - start
    results["single_client_sync_tasks_per_s"] = n / dt
    print(f"single-client sync tasks: {_rate(n, dt)}")

    ray_tpu.get([noop.remote() for _ in range(200)])  # phase warmup
    n = 1000
    start = time.perf_counter()
    ray_tpu.get([noop.remote() for _ in range(n)])
    dt = time.perf_counter() - start
    results["async_tasks_per_s"] = n / dt
    print(f"1:N async tasks:          {_rate(n, dt)}")

    actor = Actor.remote()
    ray_tpu.get([actor.noop.remote() for _ in range(50)])
    n = 500
    start = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(actor.noop.remote())
    dt = time.perf_counter() - start
    results["sync_actor_calls_per_s"] = n / dt
    print(f"sync actor calls:         {_rate(n, dt)}")

    n = 2000
    start = time.perf_counter()
    ray_tpu.get([actor.noop.remote() for _ in range(n)])
    dt = time.perf_counter() - start
    results["async_actor_calls_per_s"] = n / dt
    print(f"async actor calls:        {_rate(n, dt)}")

    payload = np.zeros(8 * 1024 * 1024, dtype=np.uint8)  # 8 MiB
    n = 20
    start = time.perf_counter()
    refs = [ray_tpu.put(payload) for _ in range(n)]
    dt = time.perf_counter() - start
    gib = n * payload.nbytes / dt / 1e9
    results["put_gbps"] = gib
    print(f"put throughput (8MiB):    {gib:.2f} GB/s")

    start = time.perf_counter()
    for ref in refs:
        ray_tpu.get(ref)
    dt = time.perf_counter() - start
    gib = n * payload.nbytes / dt / 1e9
    results["get_gbps"] = gib
    print(f"get throughput (8MiB):    {gib:.2f} GB/s")

    if owns_cluster:
        ray_tpu.shutdown()
    return results


if __name__ == "__main__":
    main()

"""Worker process — executes tasks and hosts actors.

Role-equivalent of the reference's worker side of the core worker:
task_receiver.cc / actor_scheduling_queue.cc / concurrency_group_manager.cc
[N20] plus the Python execution callback in _raylet.pyx [N30].

Execution runs on dedicated executor threads (the RPC loop stays free),
actor calls are ordered per caller by sequence number, and async actor
methods run on a separate asyncio loop (the reference's async-actor fibers).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import inspect
import json
import os
import sys
import threading
import traceback
from typing import Any

from ray_tpu import exceptions
from ray_tpu._private import serialization
from ray_tpu._private.config import global_config
from ray_tpu._private.core_context import CoreContext
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.rpc import RpcClient


class WorkerRuntime:
    def __init__(self) -> None:
        self.ctx = CoreContext(
            job_id=os.environ["RAYTPU_JOB_ID"],
            node_id=os.environ["RAYTPU_NODE_ID"],
            controller_addr=tuple(json.loads(os.environ["RAYTPU_CONTROLLER"])),
            agent_addr=tuple(json.loads(os.environ["RAYTPU_AGENT"])),
            store_info=json.loads(os.environ["RAYTPU_STORE"]),
            is_driver=False,
            worker_id=os.environ["RAYTPU_WORKER_ID"],
        )
        self.executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="exec"
        )
        self._async_loop: asyncio.AbstractEventLoop | None = None
        self.actor_instance: Any = None
        self.actor_spec: dict | None = None
        # per-caller ordered queues (actor_scheduling_queue.cc)
        self._order: dict[str, dict] = {}
        self._fn_cache: dict[str, Any] = {}

    # ------------------------------------------------------------------
    def start(self) -> None:
        ctx = self.ctx
        for method in ("push_task", "push_actor_task", "create_actor", "exit"):
            ctx.core_server.route(method, getattr(self, f"rpc_{method}"))
        ctx.connect()
        # Make the global API (ray_tpu.get/put/remote...) work inside tasks.
        from ray_tpu._private import worker as worker_mod

        worker_mod.set_global_context(ctx, is_driver=False)
        ctx.io.run(self._register_with_agent())

    async def _register_with_agent(self) -> None:
        await self.ctx.agent.call(
            "register_worker",
            {"worker_id": self.ctx.worker_id, "address": list(self.ctx.address)},
        )

    def _async_exec_loop(self) -> asyncio.AbstractEventLoop:
        if self._async_loop is None:
            loop = asyncio.new_event_loop()
            thread = threading.Thread(
                target=loop.run_forever, name="actor-async", daemon=True
            )
            thread.start()
            self._async_loop = loop
        return self._async_loop

    # ------------------------------------------------------------------
    # function / class resolution via the controller KV (function table)
    # ------------------------------------------------------------------
    async def _load_callable(self, function_id: str) -> Any:
        """Fetch+cache from the controller KV function table. Runs on the io
        loop (must not block it with sync ctx calls)."""
        cached = self._fn_cache.get(function_id)
        if cached is not None:
            return cached
        resp = await self.ctx.controller.call(
            "kv_get", {"namespace": "funcs", "key": function_id}
        )
        if resp["status"] != "ok":
            raise RuntimeError(f"function {function_id} not found in function table")
        fn = serialization.loads_function(resp["value"])
        self._fn_cache[function_id] = fn
        return fn

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def _resolve_args(self, payload) -> tuple[tuple, dict]:
        def resolver(ref_id, owner_address):
            ref = ObjectRef(ref_id, owner_address, runtime=self.ctx)
            self.ctx._note_borrow(ref_id, owner_address)
            return ref

        args, kwargs = serialization.deserialize(payload, resolver, zero_copy=False)
        # Top-level ObjectRef args are resolved to values before invocation
        # (reference semantics; nested refs stay refs).
        args = tuple(
            self.ctx.get(a) if isinstance(a, ObjectRef) else a for a in args
        )
        kwargs = {
            k: self.ctx.get(v) if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        return args, kwargs

    def _package_returns(self, spec: dict, values: list[Any]) -> list[dict]:
        cfg = global_config()
        out = []
        for index, value in enumerate(values):
            payload, _ = serialization.serialize(value)
            if len(payload) <= cfg.max_direct_call_object_size:
                out.append({"kind": "inline", "data": payload})
            else:
                object_id = f"obj-{spec['task_id']}-r{index}"
                try:
                    self.ctx.store.put(object_id, payload)
                except FileExistsError:
                    pass
                out.append(
                    {
                        "kind": "shm",
                        "size": len(payload),
                        "location": self.ctx._local_location(),
                    }
                )
        return out

    def _execute(self, spec: dict, fn: Any, is_method: bool) -> dict:
        name = spec.get("name", "task")
        try:
            args, kwargs = self._resolve_args(spec["args"])
            if inspect.iscoroutinefunction(fn):
                loop = self._async_exec_loop()
                value = asyncio.run_coroutine_threadsafe(
                    fn(*args, **kwargs), loop
                ).result()
            else:
                value = fn(*args, **kwargs)
            num_returns = spec.get("num_returns", 1)
            values = [value] if num_returns == 1 else list(value)
            return {"status": "ok", "returns": self._package_returns(spec, values)}
        except Exception:
            err = exceptions.TaskError(name, traceback.format_exc())
            payload, _ = serialization.serialize(err)
            return {"status": "error", "error": payload}

    # ------------------------------------------------------------------
    # RPC handlers
    # ------------------------------------------------------------------
    async def rpc_push_task(self, conn, spec) -> dict:
        fn = await self._load_callable(spec["function_id"])
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self.executor, self._execute, spec, fn, False
        )

    async def rpc_create_actor(self, conn, payload) -> dict:
        spec = payload["spec"]
        try:
            cls = await self._load_callable(spec["class_id"])
            concurrency = spec.get("max_concurrency", 1)
            if concurrency > 1:
                self.executor = concurrent.futures.ThreadPoolExecutor(
                    max_workers=concurrency, thread_name_prefix="exec"
                )
            loop = asyncio.get_running_loop()

            def instantiate():
                # Arg resolution may ray_tpu.get() — must run off the io loop.
                args, kwargs = (
                    self._resolve_args(payload["creation_args"])
                    if payload.get("creation_args")
                    else ((), {})
                )
                self.actor_instance = cls(*args, **kwargs)

            await loop.run_in_executor(self.executor, instantiate)
            self.actor_spec = spec
            return {"status": "ok"}
        except Exception:
            return {"status": "error", "error": traceback.format_exc()}

    async def rpc_push_actor_task(self, conn, spec) -> dict:
        caller = spec.get("caller_id", "?")
        seq = spec.get("seq", 0)
        state = self._order.get(caller)
        if state is None:
            # Baseline on the first seq seen from this caller: after an actor
            # restart the caller's counter does not reset, so "first seen" is
            # the correct start of this incarnation's stream.
            state = self._order[caller] = {"expected": seq, "waiters": {}}
        # Order per caller: wait until all earlier seqs have *started*
        # (actor_scheduling_queue.cc). A bounded wait guards against gaps
        # from callers whose earlier submissions died with a previous
        # incarnation.
        while seq > state["expected"]:
            event = state["waiters"].setdefault(seq, asyncio.Event())
            try:
                await asyncio.wait_for(event.wait(), timeout=5.0)
            except asyncio.TimeoutError:
                state["expected"] = seq
                break
        state["expected"] = max(state["expected"], seq + 1)
        for s, ev in list(state["waiters"].items()):
            if s <= state["expected"]:
                ev.set()
                state["waiters"].pop(s, None)
        method_name = spec["method"]
        if self.actor_instance is None:
            payload, _ = serialization.serialize(
                exceptions.ActorDiedError("actor not initialized")
            )
            return {"status": "error", "error": payload}
        if method_name == "__ray_terminate__":
            asyncio.get_running_loop().call_later(0.05, os._exit, 0)
            return {"status": "ok", "returns": [{"kind": "inline", "data": serialization.serialize(None)[0]}]}
        method = getattr(self.actor_instance, method_name, None)
        if method is None:
            payload, _ = serialization.serialize(
                AttributeError(f"actor has no method {method_name!r}")
            )
            return {"status": "error", "error": payload}
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self.executor, self._execute, spec, method, True
        )

    async def rpc_exit(self, conn, payload) -> dict:
        asyncio.get_running_loop().call_later(0.05, os._exit, 0)
        return {"status": "ok"}


def main() -> None:
    runtime = WorkerRuntime()
    runtime.start()
    # Park the main thread; all work happens on the io/executor threads.
    threading.Event().wait()


if __name__ == "__main__":
    main()

"""Worker process — executes tasks and hosts actors.

Role-equivalent of the reference's worker side of the core worker:
task_receiver.cc / actor_scheduling_queue.cc / concurrency_group_manager.cc
[N20] plus the Python execution callback in _raylet.pyx [N30].

Execution runs on dedicated executor threads (the RPC loop stays free),
actor calls are ordered per caller by sequence number, and async actor
methods run on a separate asyncio loop (the reference's async-actor fibers).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import contextlib
import inspect
import json
import os
import sys
import queue
import threading
import time as _time
import traceback
from typing import Any

from ray_tpu import exceptions
from ray_tpu._private import serialization
from ray_tpu._private.config import global_config
from ray_tpu.util import tracing
from ray_tpu._private.core_context import CoreContext
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.rpc import RpcClient


def _peak_rss_bytes() -> int:
    """Process high-water RSS via getrusage — ~1µs, cheap enough for the
    per-task attribution hot path (a psutil read here would dominate a
    no-op task and blow the telemetry overhead budget). Linux reports
    ru_maxrss in KiB; macOS in bytes."""
    try:
        import resource as _resource

        peak = _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss
        return peak if sys.platform == "darwin" else peak * 1024
    except Exception:  # rtlint: disable=swallowed-exception - no resource module (non-posix): report zero
        return 0


class WorkerRuntime:
    def __init__(self) -> None:
        self.ctx = CoreContext(
            job_id=os.environ["RAYTPU_JOB_ID"],
            node_id=os.environ["RAYTPU_NODE_ID"],
            controller_addr=tuple(json.loads(os.environ["RAYTPU_CONTROLLER"])),
            agent_addr=tuple(json.loads(os.environ["RAYTPU_AGENT"])),
            store_info=json.loads(os.environ["RAYTPU_STORE"]),
            is_driver=False,
            worker_id=os.environ["RAYTPU_WORKER_ID"],
        )
        self.executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="exec"
        )
        self._async_loop: asyncio.AbstractEventLoop | None = None
        self._async_sem: "asyncio.Semaphore | None" = None
        self._actor_concurrency = 1
        self.actor_instance: Any = None
        self.actor_spec: dict | None = None
        # per-caller ordered queues (actor_scheduling_queue.cc)
        self._order: dict[str, dict] = {}
        self._fn_cache: dict[str, Any] = {}
        self._task_event_lock = threading.Lock()
        # Cancellation state (reference: task_receiver.cc cancel path +
        # the ray.cancel KeyboardInterrupt convention). Normal tasks run on
        # the MAIN thread so SIGINT interrupts even blocking C calls
        # (time.sleep etc.) — exactly how the reference worker does it;
        # executor threads (sync actor tasks) get best-effort async-exc.
        self._running_exec: dict = {}      # task_id -> thread ident
        self._running_async: dict = {}     # task_id -> coroutine future
        self._cancelled_pending: set = set()
        self._main_work: "queue.Queue" = queue.Queue()
        self._main_ident: int | None = None
        self._main_executing = False
        self._main_current_task: str | None = None
        self._cancel_target: str | None = None
        self._task_events_last_flush = 0.0
        # compiled-graph state: dag_id → resident rtdag runtime (stage
        # loops + channels + per-dag device group), dag/executor.py
        self._dag_runtimes: dict = {}
        # Fast execution lane (native exec queue, task_receiver.cc role):
        # push_task/push_actor_task frames bypass asyncio; the main thread
        # consumes them via rt_exec_next. Ineligible frames bounce back to
        # the asyncio handlers.
        self._engine = None
        self._fast_mode = False
        self._inject_lock = threading.Lock()
        self._next_inject = 1
        self._main_injected: dict[int, tuple] = {}
        self._bounced_actor = 0
        # guards _bounced_actor: incremented on the exec thread,
        # decremented on the io loop — bare += would lose updates and
        # either run two tasks on a max_concurrency=1 actor or wedge the
        # fast lane shut.
        self._bounce_lock = threading.Lock()
        # per-callable coroutine-ness (inspect.iscoroutinefunction costs
        # ~3us per call; keyed by __func__ so bound methods hit)
        self._coro_cache: dict = {}
        self._method_cache: dict[str, Any] = {}
        # Per-task resource attribution (ISSUE 5): tri-state TPU probe —
        # None = unknown yet, False = jax loaded but no TPU (never probe
        # again), True = TPU live (read HBM around every task).
        self._hbm_probe: bool | None = None

    # ------------------------------------------------------------------
    def start(self) -> None:
        ctx = self.ctx
        for method in (
            "push_task", "push_actor_task", "create_actor", "exit",
            "cancel_task", "dag_register", "dag_push", "dag_pop",
            "dag_teardown", "dag_snapshot", "dag_restore",
            "profiler", "stack_trace", "engine_debug", "comm_flight",
        ):
            ctx.core_server.route(method, getattr(self, f"rpc_{method}"))
        ctx.connect()
        if ctx._engine is not None:
            # Divert the task-push methods into the native exec queue —
            # they never touch the asyncio inbox (the reference's
            # task_receiver fast path). Everything else (cancel, stacks,
            # dag, create_actor, exit) stays on the asyncio server.
            self._engine = ctx._engine
            self._engine.lib.rt_exec_filter(self._engine.handle, b"push_task")
            self._engine.lib.rt_exec_filter(
                self._engine.handle, b"push_actor_task"
            )
            self._fast_mode = True
        # Make the global API (ray_tpu.get/put/remote...) work inside tasks.
        from ray_tpu._private import worker as worker_mod

        worker_mod.set_global_context(ctx, is_driver=False)
        ctx.io.run(self._register_with_agent())

    async def _register_with_agent(self) -> None:
        await self.ctx.agent.call(
            "register_worker",
            {"worker_id": self.ctx.worker_id, "address": list(self.ctx.address)},
        )

    def run_main_loop(self) -> None:
        """Main-thread task execution loop. Normal tasks run here so that
        a cancellation SIGINT raises KeyboardInterrupt inside whatever the
        task is doing — including blocking C calls."""
        import signal as _signal

        self._main_ident = threading.get_ident()
        _signal.signal(_signal.SIGINT, self._on_sigint)
        if self._fast_mode:
            self._run_fast_main_loop()
            return
        while True:
            fn, fut = self._main_work.get()
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as exc:  # noqa: BLE001 - ferry to waiter
                fut.set_exception(exc)

    def _run_fast_main_loop(self) -> None:
        from ray_tpu import _native

        fl = _native.load_fastlane()
        if fl is not None:
            self._run_fastlane_loop(fl)
        else:
            self._run_ctypes_fast_loop()

    def _run_fastlane_loop(self, fl) -> None:
        """Native fast lane (the task_receiver.cc role done properly):
        the _fastlane C extension decodes push frames, classifies
        eligibility, and encodes+sends replies — one C call in, one C
        call out per task. Python keeps pickle + the user function.
        Anything the extension can't prove simple arrives as a bounce
        tuple and takes the asyncio path unchanged."""
        engine = self._engine
        eng = engine.handle
        ObjectRefT = ObjectRef
        fn_cache = self._fn_cache
        while True:
            item = fl.exec_next(eng, 1000)
            if item is None:
                continue
            tag = item[0]
            if tag == 1:  # plain task, pre-decoded
                (_, conn, msgid, task_id, function_id, name, args_raw,
                 num_returns, raw) = item
                try:
                    fn = fn_cache.get(function_id)
                    if fn is None:
                        self._bounce_raw(conn, msgid, b"push_task", raw)
                        continue
                    args, kwargs = self._deserialize_args(args_raw)
                    if any(isinstance(a, ObjectRefT) for a in args) or any(
                        isinstance(v, ObjectRefT) for v in kwargs.values()
                    ):
                        self._bounce_raw(conn, msgid, b"push_task", raw)
                        continue
                    spec = {
                        "task_id": task_id,
                        "name": name,
                        "num_returns": num_returns,
                    }
                    reply = self._execute(spec, fn, False, (args, kwargs))
                except Exception:
                    payload, _ = serialization.serialize(
                        exceptions.TaskError(name, traceback.format_exc())
                    )
                    reply = {"status": "error", "error": payload}
                self._send_fast_reply(
                    fl, eng, conn, msgid, b"push_task", reply
                )
                continue
            if tag == 2:  # actor task, pre-decoded
                (_, conn, msgid, task_id, method_name, name, caller_id,
                 args_raw, num_returns, seq, raw) = item
                state = self._order.get(caller_id)
                if state is None:
                    state = self._order[caller_id] = {
                        "expected": seq, "waiters": {},
                    }
                state["expected"] = max(state["expected"], seq + 1)
                try:
                    if (
                        self.actor_instance is None
                        or method_name == "__ray_terminate__"
                        or self._actor_concurrency > 1
                        or self._bounced_actor > 0
                    ):
                        self._bounce_raw(
                            conn, msgid, b"push_actor_task", raw
                        )
                        continue
                    bound = self._method_cache.get(method_name)
                    if bound is None:
                        bound = getattr(
                            self.actor_instance, method_name, None
                        )
                        if bound is None:
                            payload, _ = serialization.serialize(
                                AttributeError(
                                    f"actor has no method {method_name!r}"
                                )
                            )
                            self._send_fast_reply(
                                fl, eng, conn, msgid, b"push_actor_task",
                                {"status": "error", "error": payload},
                            )
                            continue
                        self._method_cache[method_name] = bound
                    fn_key = getattr(bound, "__func__", bound)
                    is_coro = self._coro_cache.get(fn_key)
                    if is_coro is None:
                        is_coro = inspect.iscoroutinefunction(bound)
                        self._coro_cache[fn_key] = is_coro
                    if is_coro:
                        self._bounce_raw(
                            conn, msgid, b"push_actor_task", raw
                        )
                        continue
                    args, kwargs = self._deserialize_args(args_raw)
                    if any(isinstance(a, ObjectRefT) for a in args) or any(
                        isinstance(v, ObjectRefT) for v in kwargs.values()
                    ):
                        self._bounce_raw(
                            conn, msgid, b"push_actor_task", raw
                        )
                        continue
                    spec = {
                        "task_id": task_id,
                        "name": name,
                        "num_returns": num_returns,
                    }
                    reply = self._execute(spec, bound, True, (args, kwargs))
                except Exception:
                    payload, _ = serialization.serialize(
                        exceptions.TaskError(name, traceback.format_exc())
                    )
                    reply = {"status": "error", "error": payload}
                self._send_fast_reply(
                    fl, eng, conn, msgid, b"push_actor_task", reply
                )
                continue
            if tag == 0:  # injected Python work item
                pair = self._main_injected.pop(item[1], None)
                if pair is None:
                    continue
                fn, fut = pair
                if not fut.set_running_or_notify_cancel():
                    continue
                try:
                    fut.set_result(fn())
                except BaseException as exc:  # noqa: BLE001
                    fut.set_exception(exc)
                continue
            if tag == 4:  # engine stopping
                return
            # tag == 3: ineligible frame — full Python decode + asyncio.
            # A frame even the full codec cannot decode must reply with a
            # TaskError, not kill this thread (a dead fast lane hangs
            # every subsequent task with no reply).
            _, conn, msgid, method, payload = item
            try:
                self._bounce_raw(conn, msgid, method, payload)
            except Exception:
                err, _ = serialization.serialize(
                    exceptions.TaskError(
                        method.decode("utf-8", "replace"),
                        traceback.format_exc(),
                    )
                )
                self._send_fast_reply(
                    fl, eng, conn, msgid, method,
                    {"status": "error", "error": err},
                )

    def _bounce_raw(self, conn, msgid, method, payload) -> None:
        """Decode a raw frame with the full typed codec and hand it to
        the asyncio handler (the fastlane twin of the ctypes loop's
        inline bounce decisions)."""
        from ray_tpu._private import wire_gen

        if method == b"push_task":
            spec = wire_gen.decode_task_spec(payload)
            self._bounce(conn, msgid, method, "push_task", spec)
        else:
            spec = wire_gen.decode_actor_task_spec(payload)
            caller = spec.get("caller_id", "?")
            seq = spec.get("seq", 0)
            state = self._order.get(caller)
            if state is None:
                state = self._order[caller] = {
                    "expected": seq, "waiters": {},
                }
            state["expected"] = max(state["expected"], seq + 1)
            self._bounce(conn, msgid, method, "push_actor_task", spec,
                         actor=True)

    def _send_fast_reply(
        self, fl, eng, conn, msgid, method, reply
    ) -> None:
        from ray_tpu._private import wire_gen

        if reply is None:
            return
        if reply.get("status") == "ok":
            rets = reply.get("returns")
            if (
                rets is not None
                and len(rets) == 1
                and rets[0].get("kind") == "inline"
            ):
                fl.reply_inline(eng, conn, msgid, method, rets[0]["data"])
                return
        fl.reply_raw(
            eng, conn, msgid, method, wire_gen.encode_task_reply(reply)
        )

    def _run_ctypes_fast_loop(self) -> None:
        """Fast-lane twin of the loop above: consumes the native exec
        queue (diverted push frames + injected io-loop work) in arrival
        order. Decode via the typed wire schema, execute, reply — all on
        this thread; the asyncio loop is only involved for bounced frames.
        (Fallback when the _fastlane extension is unavailable.)
        """
        import ctypes

        from ray_tpu import _native
        from ray_tpu._private import wire_gen
        from ray_tpu._private.rpc import REP

        engine = self._engine
        lib = _native.load()  # CDLL: rt_exec_next blocks with GIL released
        view = _native.RtMsgView()
        while True:
            rc = lib.rt_exec_next(engine.handle, 1000, ctypes.byref(view))
            if rc == 0:
                continue
            if rc == -1:
                return  # engine stopped: process is shutting down
            if view.kind == 253:  # injected Python work item
                tag = view.msgid
                lib.rt_msg_free(view.opaque)
                pair = self._main_injected.pop(tag, None)
                if pair is None:
                    continue
                fn, fut = pair
                if not fut.set_running_or_notify_cancel():
                    continue
                try:
                    fut.set_result(fn())
                except BaseException as exc:  # noqa: BLE001
                    fut.set_exception(exc)
                continue
            conn = view.conn
            msgid = view.msgid
            method = (
                ctypes.string_at(view.method, view.mlen) if view.mlen else b""
            )
            raw = (
                ctypes.string_at(view.payload, view.plen) if view.plen else b""
            )
            lib.rt_msg_free(view.opaque)
            try:
                if method == b"push_task":
                    reply = self._fast_push_task(conn, msgid, method, raw)
                else:
                    reply = self._fast_push_actor_task(
                        conn, msgid, method, raw
                    )
            except Exception:
                payload, _ = serialization.serialize(
                    exceptions.TaskError("fast-lane", traceback.format_exc())
                )
                reply = {"status": "error", "error": payload}
            if reply is not None:
                out = wire_gen.encode_task_reply(reply)
                if engine.pylib.rt_exec_pending(engine.handle) > 0:
                    # More work queued: buffer the reply for the engine
                    # thread's coalesced writev instead of paying an
                    # inline syscall (+ scheduler preemption) per task.
                    engine.pylib.rt_send_buf(
                        engine.handle, conn, REP, msgid,
                        method, len(method), out, len(out),
                    )
                else:
                    engine.send(conn, REP, msgid, method, out)

    def _fast_push_task(self, conn, msgid, method, raw):
        """Execute a push_task frame on this thread, or bounce it to the
        asyncio handler (cross-language, cold function cache, ref args —
        dependency resolution must never block the main lane: a pipelined
        upstream task could be queued right behind us)."""
        from ray_tpu._private import wire_gen

        spec = wire_gen.decode_task_spec(raw)
        if spec.get("cross_language") or spec.get("has_ref_args"):
            # has_ref_args: the submitter's hint skips deserializing a
            # payload we would bounce anyway (the scan below still guards
            # against third-party clients that omit the hint).
            self._bounce(conn, msgid, method, "push_task", spec)
            return None
        fn = self._fn_cache.get(spec["function_id"])
        if fn is None:
            self._bounce(conn, msgid, method, "push_task", spec)
            return None
        args, kwargs = self._deserialize_args(spec["args"])
        if any(isinstance(a, ObjectRef) for a in args) or any(
            isinstance(v, ObjectRef) for v in kwargs.values()
        ):
            self._bounce(conn, msgid, method, "push_task", spec)
            return None
        return self._execute(spec, fn, False, (args, kwargs))

    def _fast_push_actor_task(self, conn, msgid, method, raw):
        """Execute an actor call on this thread when the actor is a plain
        sync max_concurrency=1 actor; otherwise bounce. Frames arrive
        per-conn FIFO and submitters write in seq order, so arrival order
        IS seq order (the C++ conn queue is the ordered actor queue); a
        gap only appears when an earlier submission died with a previous
        incarnation — baseline forward like the asyncio path does."""
        from ray_tpu._private import wire_gen

        spec = wire_gen.decode_actor_task_spec(raw)
        caller = spec.get("caller_id", "?")
        seq = spec.get("seq", 0)
        state = self._order.get(caller)
        if state is None:
            state = self._order[caller] = {"expected": seq, "waiters": {}}
        state["expected"] = max(state["expected"], seq + 1)
        method_name = spec["method"]
        if (
            self.actor_instance is None
            or method_name == "__ray_terminate__"
            or self._actor_concurrency > 1
            or self._bounced_actor > 0
            or spec.get("has_ref_args")
        ):
            self._bounce(conn, msgid, method, "push_actor_task", spec,
                         actor=True)
            return None
        bound = self._method_cache.get(method_name)
        if bound is None:
            bound = getattr(self.actor_instance, method_name, None)
            if bound is None:
                payload, _ = serialization.serialize(
                    AttributeError(f"actor has no method {method_name!r}")
                )
                return {"status": "error", "error": payload}
            self._method_cache[method_name] = bound
        fn_key = getattr(bound, "__func__", bound)
        is_coro = self._coro_cache.get(fn_key)
        if is_coro is None:
            is_coro = inspect.iscoroutinefunction(bound)
            self._coro_cache[fn_key] = is_coro
        if is_coro:
            self._bounce(conn, msgid, method, "push_actor_task", spec,
                         actor=True)
            return None
        args, kwargs = self._deserialize_args(spec["args"])
        if any(isinstance(a, ObjectRef) for a in args) or any(
            isinstance(v, ObjectRef) for v in kwargs.values()
        ):
            self._bounce(conn, msgid, method, "push_actor_task", spec,
                         actor=True)
            return None
        return self._execute(spec, bound, True, (args, kwargs))

    def _bounce(self, conn, msgid, method, handler_name, spec, actor=False):
        """Hand a frame the fast lane must not run to the asyncio handler;
        the reply is sent from the io loop. While a bounced actor task is
        outstanding, later actor frames bounce too so a max_concurrency=1
        actor never runs two tasks at once."""
        from ray_tpu._private import wire_gen
        from ray_tpu._private.rpc import REP, spawn_task

        if actor:
            with self._bounce_lock:
                self._bounced_actor += 1
        handler = getattr(self, f"rpc_{handler_name}")
        engine = self._engine

        async def run():
            try:
                try:
                    reply = await handler(None, spec)
                except Exception:
                    payload, _ = serialization.serialize(
                        exceptions.TaskError(
                            spec.get("name", "task"), traceback.format_exc()
                        )
                    )
                    reply = {"status": "error", "error": payload}
                try:
                    engine.send(
                        conn, REP, msgid, method,
                        wire_gen.encode_task_reply(reply),
                    )
                except Exception:  # rtlint: disable=swallowed-exception - conn died: nothing more to tell the peer
                    pass  # conn died: nothing more to tell the peer
            finally:
                if actor:
                    with self._bounce_lock:
                        self._bounced_actor -= 1

        self.ctx.io.loop.call_soon_threadsafe(spawn_task, run())

    def _on_sigint(self, signum, frame) -> None:
        # Only deliver while the TARGETED task is executing: a SIGINT that
        # lands after the target finished (and another task started) must
        # not cancel the wrong task — nor kill the idle worker loop.
        if (
            self._main_executing
            and self._cancel_target is not None
            and self._main_current_task == self._cancel_target
        ):
            self._cancel_target = None
            raise KeyboardInterrupt

    async def _run_on_main(self, fn) -> dict:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        if self._fast_mode:
            with self._inject_lock:
                tag = self._next_inject
                self._next_inject = (self._next_inject % 0xFFFFFFF0) + 1
                self._main_injected[tag] = (fn, fut)
            self._engine.pylib.rt_exec_inject(self._engine.handle, tag)
        else:
            self._main_work.put((fn, fut))
        return await asyncio.wrap_future(fut)

    def _async_exec_loop(self) -> asyncio.AbstractEventLoop:
        if self._async_loop is None:
            loop = asyncio.new_event_loop()
            thread = threading.Thread(
                target=loop.run_forever, name="actor-async", daemon=True
            )
            thread.start()
            self._async_loop = loop
        return self._async_loop

    # ------------------------------------------------------------------
    # function / class resolution via the controller KV (function table)
    # ------------------------------------------------------------------
    async def _load_callable(self, function_id: str) -> Any:
        """Fetch+cache from the controller KV function table. Runs on the io
        loop (must not block it with sync ctx calls)."""
        cached = self._fn_cache.get(function_id)
        if cached is not None:
            return cached
        # Brief retry: the owner's kv_put may still be in flight when the
        # first task referencing the function reaches a fresh worker.
        for attempt in range(10):
            resp = await self.ctx.controller.call(
                "kv_get", {"namespace": "funcs", "key": function_id}
            )
            if resp["status"] == "ok":
                break
            await asyncio.sleep(0.2)
        if resp["status"] != "ok":
            raise RuntimeError(f"function {function_id} not found in function table")
        # Functions/classes may close over ObjectRefs — resolve them the
        # same way task args do (register the borrow with the owner).
        def resolver(ref_id, owner_address):
            ref = ObjectRef(ref_id, owner_address, runtime=self.ctx)
            self.ctx._note_borrow(ref_id, owner_address)
            return ref

        fn = serialization.loads_function(resp["value"], ref_resolver=resolver)
        self._fn_cache[function_id] = fn
        return fn

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def _deserialize_args(self, payload) -> tuple[tuple, dict]:
        """Deserialize an args payload, registering borrows for contained
        ObjectRefs (shared by the sync and async resolution paths)."""
        def resolver(ref_id, owner_address):
            ref = ObjectRef(ref_id, owner_address, runtime=self.ctx)
            self.ctx._note_borrow(ref_id, owner_address)
            return ref

        return serialization.deserialize(payload, resolver, zero_copy=False)

    def _resolve_args(self, payload) -> tuple[tuple, dict]:
        args, kwargs = self._deserialize_args(payload)
        # Top-level ObjectRef args are resolved to values before invocation
        # (reference semantics; nested refs stay refs).
        args = tuple(
            self.ctx.get(a) if isinstance(a, ObjectRef) else a for a in args
        )
        kwargs = {
            k: self.ctx.get(v) if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        return args, kwargs

    def _package_returns(self, spec: dict, values: list[Any]) -> list[dict]:
        cfg = global_config()
        out = []
        for index, value in enumerate(values):
            if value is None:
                out.append(
                    {"kind": "inline", "data": serialization.NONE_PAYLOAD}
                )
                continue
            payload, _ = serialization.serialize(value)
            if len(payload) <= cfg.max_direct_call_object_size:
                out.append({"kind": "inline", "data": payload})
            else:
                object_id = f"obj-{spec['task_id']}-r{index}"
                try:
                    self.ctx.store.put(object_id, payload)
                except FileExistsError:
                    pass
                out.append(
                    {
                        "kind": "shm",
                        "size": len(payload),
                        "location": self.ctx._local_location(),
                    }
                )
        return out

    def _execute(
        self,
        spec: dict,
        fn: Any,
        is_method: bool,
        preresolved: tuple | None = None,
    ) -> dict:
        name = spec.get("name", "task")
        task_id = spec.get("task_id")
        if task_id in self._cancelled_pending:
            # Cancelled while queued at this worker (e.g. behind an actor's
            # ordered/concurrency queue).
            self._cancelled_pending.discard(task_id)
            self._record_task_event(spec, "CANCELLED")
            return {"status": "cancelled"}
        # RUNNING is recorded eagerly — a hung task must be visible to the
        # state API while stuck; the terminal record additionally carries
        # start_ts so one record describes the whole span.
        start_ts = _time.time()
        self._record_task_event(spec, "RUNNING")
        on_main = threading.get_ident() == self._main_ident
        self._running_exec[task_id] = threading.get_ident()
        if on_main:
            self._main_current_task = task_id
            self._main_executing = True
        trace_ctx = spec.get("trace_ctx") if tracing.enabled() else None
        arrival_ns = spec.pop("_arrival_ns", None)
        if trace_ctx and arrival_ns:
            # In-actor queue wait: time between the call frame arriving at
            # this worker and the method actually starting.
            tracing.emit(
                "queue_wait", trace_ctx, start_ns=arrival_ns,
                task_id=task_id, worker_id=self.ctx.worker_id,
            )
        if trace_ctx is None:
            return self._execute_inner(
                spec, fn, preresolved, name, task_id, on_main, start_ts
            )
        # begin/finish fast path + explicit contextvar write: user code
        # runs inside, so nested .remote() calls must see this span as
        # the ambient parent (what span() would have provided), but the
        # contextmanager machinery is per-task overhead.
        tspan = tracing.begin(
            f"execute {name}", parent=trace_ctx,
            task_id=task_id, worker_id=self.ctx.worker_id,
        )
        token = tracing.set_current(tspan)
        try:
            return self._execute_inner(
                spec, fn, preresolved, name, task_id, on_main, start_ts,
                trace_span=tspan,
            )
        except BaseException as exc:
            tspan.set_error(exc)
            raise
        finally:
            tracing.reset_current(token)
            tracing.finish(tspan)

    def _execute_inner(
        self, spec, fn, preresolved, name, task_id, on_main, start_ts=None,
        trace_span=None,
    ) -> dict:
        rss0 = _peak_rss_bytes()
        hbm0 = self._hbm_used()
        try:
            if preresolved is not None:
                args, kwargs = preresolved
            elif trace_span is not None and spec.get("has_ref_args"):
                # fetch_args times DEPENDENCY resolution; inline-only args
                # resolve in-place, so the span would only add per-task
                # overhead without information.
                with tracing.span(
                    "fetch_args", parent=spec.get("trace_ctx"),
                    task_id=task_id,
                ):
                    args, kwargs = self._resolve_args(spec["args"])
            else:
                args, kwargs = self._resolve_args(spec["args"])
            fn_key = getattr(fn, "__func__", fn)
            is_coro = self._coro_cache.get(fn_key)
            if is_coro is None:
                is_coro = inspect.iscoroutinefunction(fn)
                self._coro_cache[fn_key] = is_coro
            if is_coro:
                loop = self._async_exec_loop()
                cfut = asyncio.run_coroutine_threadsafe(
                    fn(*args, **kwargs), loop
                )
                self._running_async[task_id] = cfut
                try:
                    value = cfut.result()
                finally:
                    self._running_async.pop(task_id, None)
            else:
                value = fn(*args, **kwargs)
            num_returns = spec.get("num_returns", 1)
            values = [value] if num_returns == 1 else list(value)
            self._record_task_event(
                spec, "FINISHED", start_ts,
                self._task_resources(rss0, hbm0, trace_span),
            )
            if trace_span is not None:
                # begin/finish fast path: parent is explicit and no user
                # code runs inside, so the contextvar write of span() is
                # pure per-task overhead here.
                pspan = tracing.begin(
                    "put_result", parent=spec.get("trace_ctx"),
                    task_id=task_id, num_returns=num_returns,
                )
                try:
                    returns = self._package_returns(spec, values)
                finally:
                    tracing.finish(pspan)
            else:
                returns = self._package_returns(spec, values)
            return {"status": "ok", "returns": returns}
        except (KeyboardInterrupt, concurrent.futures.CancelledError,
                asyncio.CancelledError):
            # KeyboardInterrupt: raised by rpc_cancel_task via SIGINT /
            # async-exc (ray.cancel convention — the task sees it).
            # CancelledError: an async task's coroutine was cancelled.
            if trace_span is not None:
                trace_span.status = "cancelled"
            self._record_task_event(spec, "CANCELLED", start_ts)
            return {"status": "cancelled"}
        except Exception as exc:
            if trace_span is not None:
                trace_span.set_error(exc)
            self._record_task_event(
                spec, "FAILED", start_ts,
                self._task_resources(rss0, hbm0, trace_span),
            )
            err = exceptions.TaskError(name, traceback.format_exc())
            payload, _ = serialization.serialize(err)
            return {"status": "error", "error": payload}
        finally:
            if on_main:
                self._main_executing = False
                self._main_current_task = None
            self._running_exec.pop(task_id, None)

    def _hbm_used(self) -> int | None:
        """Local-TPU HBM bytes in use, or None when not on TPU. The probe
        is tri-state cached: once jax is loaded without TPU devices this
        is a single attribute check per task forever after."""
        if self._hbm_probe is False:
            return None
        mod = sys.modules.get("jax")
        if mod is None:
            return None
        try:
            devices = [
                d for d in mod.local_devices()
                if getattr(d, "platform", "") == "tpu"
            ]
            if not devices:
                self._hbm_probe = False
                return None
            self._hbm_probe = True
            return sum(
                int((d.memory_stats() or {}).get("bytes_in_use", 0))
                for d in devices
            )
        except Exception:
            self._hbm_probe = False
            return None

    def _task_resources(
        self, rss0: int, hbm0: int | None, trace_span=None
    ) -> dict:
        """Per-task resource attribution (ISSUE 5). ru_maxrss is a process
        high-water mark, so ``rss_delta`` is how much THIS task raised it —
        the "which task ate the memory" signal — and ``peak_rss`` is the
        worker's peak during/before the task. Also stamped into the PR-4
        execute span so traces carry the memory story alongside latency."""
        peak = _peak_rss_bytes()
        res = {"peak_rss": peak, "rss_delta": max(0, peak - rss0)}
        if hbm0 is not None:
            hbm1 = self._hbm_used()
            if hbm1 is not None:
                res["hbm_delta"] = hbm1 - hbm0
        if trace_span is not None:
            trace_span.attributes.update(res)
        return res

    def _record_task_event(
        self, spec: dict, state: str, start_ts: float | None = None,
        resources: dict | None = None,
    ) -> None:
        """Task lifecycle events feed the state API + `ray_tpu timeline`
        (reference: profile_event.cc → gcs_task_manager.cc [N5]). Terminal
        events carry ``start_ts`` so one record describes the whole span,
        plus the per-task resource attribution when measured."""
        with self._task_event_lock:
            # Hot path appends a tuple; the flush below expands it into the
            # full record (the reference buffers a ring of slim events and
            # reports periodically, gcs_task_manager) — building an 8-key
            # dict per lifecycle event costs more than the task envelope.
            self.ctx._task_events.append(
                (spec.get("task_id"), spec.get("name"), state, start_ts,
                 _time.time(), resources)
            )
            # Batch: size- or time-triggered, never per-event.
            now = _time.monotonic()
            due = (
                len(self.ctx._task_events) >= 100
                or now - self._task_events_last_flush > 1.0
            )
            if not due:
                return
            slim = self.ctx._task_events[:]
            self.ctx._task_events.clear()
            self._task_events_last_flush = now
        node_id = self.ctx.node_id
        worker_id = self.ctx.worker_id
        pid = os.getpid()
        events = []
        for task_id, name, ev_state, ev_start, ts, extras in slim:
            event = {
                "task_id": task_id,
                "name": name,
                "state": ev_state,
                "node_id": node_id,
                "worker_id": worker_id,
                "pid": pid,
                "ts": ts,
            }
            if ev_start is not None:
                event["start_ts"] = ev_start
            if extras:
                event.update(extras)  # peak_rss / rss_delta / hbm_delta
            events.append(event)

        async def _flush():
            try:
                await self.ctx.controller.call(
                    "report_task_events", {"events": events}
                )
            except Exception:  # rtlint: disable=swallowed-exception - task-event uplink is advisory telemetry
                pass

        self.ctx.io.spawn(_flush())

    # ------------------------------------------------------------------
    # RPC handlers
    # ------------------------------------------------------------------
    def _collect_stacks(self) -> tuple[dict, dict]:
        """(thread stacks, parked asyncio task stacks) — native frame
        walk, no external deps."""
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        stacks = {}
        for ident, frame in frames.items():
            label = f"{names.get(ident, 'unknown')}-{ident}"
            stacks[label] = "".join(traceback.format_stack(frame))
        # Parked coroutines are invisible in thread frames — dump the io
        # loop's asyncio tasks too (where a wedged RPC handler actually is).
        coros = {}
        try:
            for task in asyncio.all_tasks():
                tb = task.get_stack(limit=6)
                coros[task.get_name()] = [
                    f"{f.f_code.co_filename}:{f.f_lineno} {f.f_code.co_name}"
                    for f in tb
                ]
        except Exception:  # rtlint: disable=swallowed-exception - stack introspection is advisory debug info
            pass
        return stacks, coros

    async def rpc_stack_trace(self, conn, payload) -> dict:
        """Live stack dump of every thread in this worker (the reference's
        dashboard 'Stack Trace' button shells out to py-spy on the worker
        pid — reporter_agent.py; in-process frames need no subprocess)."""
        stacks, coros = self._collect_stacks()
        return {
            "status": "ok",
            "pid": os.getpid(),
            "worker_id": self.ctx.worker_id,
            "current_task": self._main_current_task,
            "stacks": stacks,
            "asyncio_tasks": coros,
        }

    async def rpc_comm_flight(self, conn, payload) -> dict:
        """Hang-doctor evidence: this worker's last-N comm flight records,
        in-flight summary, local stall events, and a native stack dump —
        one round trip per rank during a cluster-wide harvest."""
        from ray_tpu.util.collective import flight

        last_n = int((payload or {}).get("last_n", 256))
        with_stacks = bool((payload or {}).get("stacks", True))
        out = {
            "status": "ok",
            "pid": os.getpid(),
            "worker_id": self.ctx.worker_id,
            "current_task": self._main_current_task,
            "records": flight.snapshot(last_n),
            "inflight": flight.inflight_summary(),
            "stalls": flight.stall_events(),
        }
        if with_stacks:
            stacks, coros = self._collect_stacks()
            out["stacks"] = stacks
            out["asyncio_tasks"] = coros
        return out

    async def rpc_engine_debug(self, conn, payload) -> dict:
        """Native transport state of every conn this worker's engine owns
        (hang forensics: wq/rbuf levels reveal lost-frame desyncs)."""
        import ctypes

        from ray_tpu._private.rpc import _NativeEngine

        try:
            engine = _NativeEngine.for_running_loop()
        except Exception as exc:
            return {"status": "error", "error": str(exc)}
        ids = (ctypes.c_longlong * 256)()
        n = engine.lib.rt_list_conns(engine.handle, ids, 256)
        conns = {}
        for i in range(n):
            out = (ctypes.c_longlong * 6)()
            if engine.lib.rt_conn_debug(engine.handle, ids[i], out) == 0:
                conns[int(ids[i])] = {
                    "wq_len": out[0], "woff": out[1], "fd": out[2],
                    "closed": out[3], "bytes_queued": out[4],
                    "unparsed_rbuf": out[5],
                }
        return {"status": "ok", "pid": os.getpid(), "conns": conns,
                "owners": {c: type(o).__name__
                           for c, o in engine.owners.items()}}

    async def rpc_profiler(self, conn, payload) -> dict:
        """Profiler control surface (ISSUE 20).

        Manual actions (the original SURVEY §5.1 hook, hardened):
        ``start``/``stop`` drive a raw jax.profiler trace into a
        session-dir directory. Errors are TYPED (``code`` field):
        double-start → ``already_started``, stop-without-start →
        ``not_started``, a live coordinated capture → ``plane_active``.
        Output dirs are GC'd on every start (session-scoped TTL,
        RAY_TPU_PROFILE_DIR_TTL_S — they used to accumulate forever).

        Coordinated actions (the cluster step profiler):
        ``arm``/``status``/``collect``/``abort`` delegate to this
        worker's :class:`~ray_tpu._private.profiler.ProfilePlane` —
        step-boundary-aligned capture of device trace + host sampling
        profiler + annotation slices, harvested by the controller."""
        from ray_tpu._private import profiler as profiler_mod

        action = payload.get("action")
        plane = profiler_mod.get_plane()
        if action == "arm":
            plane.set_meta(worker_id=self.ctx.worker_id)
            return await asyncio.to_thread(plane.arm, payload)
        if action == "status":
            return plane.status()
        if action == "collect":
            return plane.collect()
        if action == "abort":
            return await asyncio.to_thread(plane.abort)
        try:
            import jax
        except Exception as exc:  # pragma: no cover - jax is baked in
            return {"status": "error", "error": f"jax unavailable: {exc}"}
        if action == "start":
            if getattr(self, "_profiling_dir", None):
                return {
                    "status": "error",
                    "code": "already_started",
                    "error": "profiler already running",
                }
            if plane.state in ("armed", "capturing"):
                return {
                    "status": "error",
                    "code": "plane_active",
                    "error": "a coordinated capture owns the profiler",
                }
            base = os.path.join(
                os.environ.get("RAYTPU_SESSION_DIR", "/tmp"), "profiles"
            )
            await asyncio.to_thread(profiler_mod.gc_profile_dirs, base)
            log_dir = payload.get("log_dir") or os.path.join(
                base, f"worker-{self.ctx.worker_id[-12:]}"
            )
            os.makedirs(log_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(log_dir)
            except Exception as exc:
                return {"status": "error", "code": "start_failed",
                        "error": str(exc)}
            self._profiling_dir = log_dir
            return {"status": "ok", "log_dir": log_dir}
        if action == "stop":
            if not getattr(self, "_profiling_dir", None):
                return {
                    "status": "error",
                    "code": "not_started",
                    "error": "profiler not running",
                }
            log_dir, self._profiling_dir = self._profiling_dir, None
            try:
                jax.profiler.stop_trace()
            except Exception as exc:
                return {"status": "error", "code": "stop_failed",
                        "error": str(exc)}
            return {"status": "ok", "log_dir": log_dir}
        return {"status": "error", "code": "unknown_action",
                "error": f"unknown action {action!r}"}

    async def rpc_push_task(self, conn, spec) -> dict:
        if spec.get("cross_language"):
            # Cross-language call (C++ worker API, reference N32 role /
            # Ray's Java→Python convention): the function is named by a
            # module-qualified ref ("pkg.module:attr"), args are plain
            # msgpack values, and returns go back inline as msgpack so a
            # non-Python caller can decode them.
            return await self._run_cross_language(spec)
        fn = await self._load_callable(spec["function_id"])
        # Resolve argument dependencies on the io loop BEFORE taking the
        # main execution lane (reference: dependency resolution precedes
        # execution — dependency_resolver.cc / raylet arg gating). With
        # pipelined pushes, a task blocking on an upstream ref while
        # HOLDING the main lane would deadlock against that upstream task
        # queued behind it on this very worker.
        try:
            if (
                tracing.enabled()
                and spec.get("trace_ctx")
                and spec.get("has_ref_args")
            ):
                # Span only when there are actual dependencies to fetch —
                # inline-args resolution is a no-op not worth a record.
                with tracing.span(
                    "fetch_args", parent=spec["trace_ctx"],
                    task_id=spec.get("task_id"),
                ):
                    preresolved = await self._resolve_args_async(spec["args"])
            else:
                preresolved = await self._resolve_args_async(spec["args"])
        except Exception:
            self._record_task_event(spec, "FAILED")
            err = exceptions.TaskError(
                spec.get("name", "task"), traceback.format_exc()
            )
            payload, _ = serialization.serialize(err)
            return {"status": "error", "error": payload}
        return await self._run_on_main(
            lambda: self._execute(spec, fn, False, preresolved)
        )

    async def _run_cross_language(self, spec: dict) -> dict:
        """Execute a cross-language task: import ``module:attr``, call with
        msgpack args, reply with msgpack values (no pickle on the wire, so
        any language speaking the wire format can drive it)."""
        import importlib

        import msgpack

        name = spec.get("name", spec.get("function_ref", "xlang-task"))
        try:
            module_name, _, attr = spec["function_ref"].partition(":")
            if not module_name or not attr:
                raise ValueError(
                    f"function_ref must be 'module:attr', got "
                    f"{spec['function_ref']!r}"
                )
            module = importlib.import_module(module_name)
            fn = module
            for part in attr.split("."):
                fn = getattr(fn, part)
            args = msgpack.unpackb(spec["args"], raw=False) or []
            self._record_task_event(spec, "RUNNING")
            # Main execution lane, like every normal task: a 1-slot worker
            # must not run a cross-language task concurrently with a
            # Python task. (Cancellation of cross-language tasks is not
            # supported yet — no _running_exec registration.)
            value = await self._run_on_main(lambda: fn(*args))
            num_returns = spec.get("num_returns", 1)
            values = [value] if num_returns == 1 else list(value)
            self._record_task_event(spec, "FINISHED")
            return {
                "status": "ok",
                "returns": [
                    {"kind": "msgpack", "data": msgpack.packb(v)}
                    for v in values
                ],
            }
        except Exception:
            self._record_task_event(spec, "FAILED")
            return {
                "status": "error",
                "error_text": f"{name}: {traceback.format_exc()}",
            }

    async def _resolve_args_async(self, payload) -> tuple[tuple, dict]:
        """Async twin of _resolve_args: awaits top-level ObjectRef args on
        the io loop instead of blocking an execution lane."""
        args, kwargs = self._deserialize_args(payload)
        args = tuple(
            [
                (await self.ctx._get_one(a)) if isinstance(a, ObjectRef) else a
                for a in args
            ]
        )
        kwargs = {
            k: (await self.ctx._get_one(v)) if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        return args, kwargs

    async def rpc_create_actor(self, conn, payload) -> dict:
        spec = payload["spec"]
        try:
            cls = await self._load_callable(spec["class_id"])
            concurrency = spec.get("max_concurrency", 1)
            self._actor_concurrency = concurrency
            self._async_sem = None  # built lazily on the io loop
            if concurrency > 1:
                self.executor = concurrent.futures.ThreadPoolExecutor(
                    max_workers=concurrency, thread_name_prefix="exec"
                )
            loop = asyncio.get_running_loop()

            def instantiate():
                # Arg resolution may ray_tpu.get() — must run off the io loop.
                args, kwargs = (
                    self._resolve_args(payload["creation_args"])
                    if payload.get("creation_args")
                    else ((), {})
                )
                self.actor_instance = cls(*args, **kwargs)

            await loop.run_in_executor(self.executor, instantiate)
            self.actor_spec = spec
            return {"status": "ok"}
        except Exception:
            return {"status": "error", "error": traceback.format_exc()}

    async def rpc_push_actor_task(self, conn, spec) -> dict:
        if tracing.enabled() and spec.get("trace_ctx"):
            # Arrival stamp: the gap to actual execution becomes the
            # in-actor queue_wait span (ordered/concurrency queue time).
            spec["_arrival_ns"] = _time.time_ns()
        caller = spec.get("caller_id", "?")
        seq = spec.get("seq", 0)
        state = self._order.get(caller)
        if state is None:
            # Baseline on the first seq seen from this caller: after an actor
            # restart the caller's counter does not reset, so "first seen" is
            # the correct start of this incarnation's stream.
            state = self._order[caller] = {"expected": seq, "waiters": {}}
        # Order per caller: wait until all earlier seqs have *started*
        # (actor_scheduling_queue.cc). A bounded wait guards against gaps
        # from callers whose earlier submissions died with a previous
        # incarnation.
        while seq > state["expected"]:
            event = state["waiters"].setdefault(seq, asyncio.Event())
            try:
                # Generous: this releases ONLY when an earlier submission
                # died with a previous actor incarnation; a short timeout
                # misfires as out-of-order execution on a loaded host.
                await asyncio.wait_for(event.wait(), timeout=30.0)
            except asyncio.TimeoutError:
                state["expected"] = seq
                break
        state["expected"] = max(state["expected"], seq + 1)
        for s, ev in list(state["waiters"].items()):
            if s <= state["expected"]:
                ev.set()
                state["waiters"].pop(s, None)
        method_name = spec["method"]
        if self.actor_instance is None:
            payload, _ = serialization.serialize(
                exceptions.ActorDiedError("actor not initialized")
            )
            return {"status": "error", "error": payload}
        if method_name == "__ray_terminate__":
            asyncio.get_running_loop().call_later(0.05, os._exit, 0)
            return {"status": "ok", "returns": [{"kind": "inline", "data": serialization.serialize(None)[0]}]}
        method = getattr(self.actor_instance, method_name, None)
        if method is None:
            payload, _ = serialization.serialize(
                AttributeError(f"actor has no method {method_name!r}")
            )
            return {"status": "error", "error": payload}
        if inspect.iscoroutinefunction(method):
            # Async actor methods run as coroutines on the dedicated actor
            # loop (reference async-actor semantics): awaiting them here
            # costs no executor thread, so long-poll style methods scale to
            # hundreds of concurrent waiters. Concurrency is bounded by the
            # same max_concurrency as sync methods, via a semaphore.
            return await self._execute_async_actor(spec, method)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self.executor, self._execute, spec, method, True
        )

    async def _execute_async_actor(self, spec: dict, method) -> dict:
        name = spec.get("name", "task")
        task_id = spec.get("task_id")
        if task_id in self._cancelled_pending:
            self._cancelled_pending.discard(task_id)
            self._record_task_event(spec, "CANCELLED")
            return {"status": "cancelled"}
        if self._async_sem is None:
            self._async_sem = asyncio.Semaphore(self._actor_concurrency)
        trace_ctx = spec.get("trace_ctx") if tracing.enabled() else None
        arrival_ns = spec.pop("_arrival_ns", None)
        async with self._async_sem:
            if trace_ctx and arrival_ns:
                tracing.emit(
                    "queue_wait", trace_ctx, start_ns=arrival_ns,
                    task_id=task_id, worker_id=self.ctx.worker_id,
                )
            start_ts = _time.time()
            self._record_task_event(spec, "RUNNING")
            if trace_ctx is None:
                return await self._async_actor_body(
                    spec, method, name, task_id, start_ts, None
                )
            with tracing.span(
                f"execute {name}", parent=trace_ctx,
                task_id=task_id, worker_id=self.ctx.worker_id,
            ) as tspan:
                return await self._async_actor_body(
                    spec, method, name, task_id, start_ts, tspan
                )

    async def _async_actor_body(
        self, spec, method, name, task_id, start_ts, trace_span
    ) -> dict:
        rss0 = _peak_rss_bytes()
        hbm0 = self._hbm_used()
        try:
            args, kwargs = await self._resolve_args_async(spec["args"])
            cfut = asyncio.run_coroutine_threadsafe(
                method(*args, **kwargs), self._async_exec_loop()
            )
            self._running_async[task_id] = cfut
            try:
                value = await asyncio.wrap_future(cfut)
            finally:
                self._running_async.pop(task_id, None)
            num_returns = spec.get("num_returns", 1)
            values = [value] if num_returns == 1 else list(value)
            self._record_task_event(
                spec, "FINISHED", start_ts,
                self._task_resources(rss0, hbm0, trace_span),
            )
            return {
                "status": "ok",
                "returns": self._package_returns(spec, values),
            }
        except (asyncio.CancelledError,
                concurrent.futures.CancelledError):
            if trace_span is not None:
                trace_span.status = "cancelled"
            self._record_task_event(spec, "CANCELLED", start_ts)
            return {"status": "cancelled"}
        except Exception as exc:
            if trace_span is not None:
                trace_span.set_error(exc)
            self._record_task_event(
                spec, "FAILED", start_ts,
                self._task_resources(rss0, hbm0, trace_span),
            )
            err = exceptions.TaskError(name, traceback.format_exc())
            payload, _ = serialization.serialize(err)
            return {"status": "error", "error": payload}

    # ------------------------------------------------------------------
    # compiled-graph (rtdag) runtime [SURVEY §2.2 "Compiled graphs"]
    # ------------------------------------------------------------------
    # The driver registers this actor's stage bundle once at compile
    # time; a resident StageLoop per stage (dag/executor.py) then moves
    # every payload over pre-opened channels (shm ring / device p2p
    # plane) — zero controller RPCs and zero per-hop notifies in steady
    # state. Only the legacy socket fallback still rides dag_push/dag_pop.

    async def rpc_dag_register(self, conn, payload) -> dict:
        from ray_tpu.dag.executor import DagRuntime

        dag_id = payload["dag_id"]
        epoch = int(payload.get("epoch", 0))
        existing = self._dag_runtimes.get(dag_id)
        if existing is not None:
            if int(getattr(existing, "epoch", 0)) >= epoch:
                return {"status": "ok"}  # idempotent re-register
            # Recovery re-register at a newer epoch: a SURVIVOR actor
            # rebuilds its loops against the re-opened channels. The old
            # runtime is stopped off-loop first (its threads may be
            # blocked in channel ops against dead peers).
            self._dag_runtimes.pop(dag_id, None)
            stop_loop = asyncio.get_running_loop()
            await stop_loop.run_in_executor(None, existing.stop)
        loop = asyncio.get_running_loop()
        ctx = self.ctx

        def _build():
            # Built OFF the io loop: the per-dag device-group rendezvous
            # blocks on controller KV round trips that themselves need
            # the loop free.
            return DagRuntime(
                ctx=ctx, dag_id=dag_id, payload=payload,
                run_stage=self._dag_call, notify_loop=loop,
            )

        try:
            runtime = await loop.run_in_executor(None, _build)
        except Exception:
            return {"status": "error", "error": traceback.format_exc()}
        self._dag_runtimes[dag_id] = runtime
        return {"status": "ok"}

    def _dag_call(self, method_name: str, args):
        """Run one stage invocation on the actor's single-width executor
        — stage loops pipeline across actors, never within one."""
        method = getattr(self.actor_instance, method_name)
        return self.executor.submit(method, *args).result()

    async def rpc_dag_push(self, conn, payload) -> dict:
        """Socket-fallback edge delivery: feed one buffered input slot."""
        runtime = self._dag_runtimes.get(payload["dag_id"])
        if runtime is None:
            return {"status": "error",
                    "error": f"dag {payload['dag_id']} not registered"}
        push_epoch = int(payload.get("epoch", 0))
        if push_epoch != int(getattr(runtime, "epoch", 0)):
            # Epoch fencing for the socket family: a pre-crash push (or
            # a stale driver) must not feed a re-opened graph.
            return {"status": "stale_epoch", "epoch": runtime.epoch}
        value = serialization.deserialize(payload["value"], zero_copy=False)
        trace = payload.get("trace")
        if trace is not None:
            # Re-wrap the sidecar trace context so the stage loop's
            # buffered-edge pop recovers it like a local edge's envelope.
            from ray_tpu.dag.channels import _TR_WIRE

            value = (_TR_WIRE, trace, value)
        try:
            runtime.feed(payload["node"], payload["slot"],
                         payload["seq"], value)
        except KeyError as exc:
            return {"status": "error", "error": str(exc)}
        return {"status": "ok"}

    async def rpc_dag_pop(self, conn, payload) -> dict:
        """Socket-fallback output pop: await the parked result for seq."""
        runtime = self._dag_runtimes.get(payload["dag_id"])
        if runtime is None:
            return {"status": "error",
                    "error": f"dag {payload['dag_id']} not registered"}
        return await runtime.pop(
            payload["seq"], payload.get("timeout", 300)
        )

    async def rpc_dag_teardown(self, conn, payload) -> dict:
        """Stop the resident loops, free consumer-owned ring slots, and
        leave the per-dag device group. Idempotent."""
        runtime = self._dag_runtimes.pop(payload["dag_id"], None)
        if runtime is not None:
            loop = asyncio.get_running_loop()
            # stop() joins threads that may be blocked in channel ops —
            # keep the io loop free while they wind down.
            await loop.run_in_executor(None, runtime.stop)
        return {"status": "ok"}

    async def rpc_dag_snapshot(self, conn, payload) -> dict:
        """Stateful-actor checkpoint hook: call ``__dag_snapshot__`` on
        the actor instance (if it defines one) and return the serialized
        blob. The driver stores blobs opaquely; ``no_hook`` lets
        stateless stages participate in all-or-nothing snapshots for
        free."""
        hook = getattr(self.actor_instance, "__dag_snapshot__", None)
        if hook is None:
            return {"status": "no_hook"}
        loop = asyncio.get_running_loop()
        try:
            # The hook runs on the actor's single-width executor (state
            # access must serialize with stage invocations), awaited
            # off-loop so a slow snapshot can't wedge the io loop.
            fut = self.executor.submit(hook)
            state_obj = await loop.run_in_executor(None, fut.result)
            blob, _ = serialization.serialize(state_obj)
        except Exception:
            return {"status": "error", "error": traceback.format_exc()}
        return {"status": "ok", "blob": blob}

    async def rpc_dag_restore(self, conn, payload) -> dict:
        """Inverse of dag_snapshot: hand the committed blob back to
        ``__dag_restore__`` — survivors roll back and replacements catch
        up to the same consistent cut before replay starts."""
        hook = getattr(self.actor_instance, "__dag_restore__", None)
        if hook is None:
            return {"status": "no_hook"}
        loop = asyncio.get_running_loop()
        try:
            state_obj = serialization.deserialize(
                payload["blob"], zero_copy=False
            )
            fut = self.executor.submit(hook, state_obj)
            await loop.run_in_executor(None, fut.result)
        except Exception:
            return {"status": "error", "error": traceback.format_exc()}
        return {"status": "ok"}

    async def rpc_cancel_task(self, conn, payload) -> dict:
        """Cancel a task on this worker (reference: CoreWorker::CancelTask →
        task_receiver). force=True kills the process (owner surfaces
        WorkerCrashedError). force=False: main-thread task → SIGINT
        (interrupts blocking C calls, reference semantics); async task →
        cancel its coroutine; sync actor-executor task → best-effort
        async-exc (reference parity: only async actor tasks are reliably
        interruptible); not-yet-started → marked so it returns cancelled
        when dequeued."""
        if payload.get("force"):
            import signal as _signal

            os.kill(os.getpid(), _signal.SIGKILL)
            return {"status": "ok"}  # unreachable
        task_id = payload.get("task_id")
        cfut = self._running_async.get(task_id)
        if cfut is not None:
            cfut.cancel()
            return {"status": "ok"}
        ident = self._running_exec.get(task_id)
        if ident is None:
            self._cancelled_pending.add(task_id)
            return {"status": "not_running"}
        if ident == self._main_ident:
            import signal as _signal

            self._cancel_target = task_id
            os.kill(os.getpid(), _signal.SIGINT)
            return {"status": "ok"}
        import ctypes

        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(ident), ctypes.py_object(KeyboardInterrupt)
        )
        return {"status": "ok"}

    async def rpc_exit(self, conn, payload) -> dict:
        asyncio.get_running_loop().call_later(0.05, os._exit, 0)
        return {"status": "ok"}


def main() -> None:
    from ray_tpu._private import chaos

    chaos.set_identity(f"worker:{os.environ.get('RAYTPU_WORKER_ID', '')}")
    runtime = WorkerRuntime()
    runtime.start()
    # The main thread is the normal-task execution lane (cancellation via
    # SIGINT lands here); RPC/io stay on their own threads.
    runtime.run_main_loop()


if __name__ == "__main__":
    main()

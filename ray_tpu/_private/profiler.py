"""Cluster step profiler — per-worker capture plane (ISSUE 20).

Every worker owns one :class:`ProfilePlane`: a small state machine
(``idle → armed → capturing → done``) the controller drives over the
PR-14 evidence-harvest fan-out (controller → node agents → workers).
Arming names a *future step boundary* so every selected rank starts its
capture at the same global step; the boundary hook rides the existing
StepStats report path (`train/_internal/step_stats.py`), so a non-train
worker pays one module-bool check per report and nothing else.

A capture gathers three layers, all bounded:

  * the ``jax.profiler`` device trace (written under the session dir;
    best-effort — a concurrent manual trace downgrades to host-only),
  * a host sampling profiler (:class:`HostSampler`): a daemon thread
    walking ``sys._current_frames()`` at ``RAY_TPU_PROFILE_HOST_HZ``,
    folding stacks in place (no per-sample allocation growth). Threads
    that exit mid-walk are skipped, the sampler never samples itself,
    and a fork (pid change) stops it — the same handle-eviction
    discipline as the memory monitor's pid-reuse fix,
  * the annotation buffer: ``step_annotation()`` slices (fwd/bwd/opt,
    per-bucket fence waits) and phase totals, which the controller merges
    into ONE Perfetto trace and feeds the ``straggler_hot_phase``
    diagnose rule.

Knobs (all env, documented in docs/observability.md):

  RAY_TPU_PROFILE_HOST_HZ           host sampler frequency   (50)
  RAY_TPU_PROFILE_MAX_S             hard cap per capture     (60)
  RAY_TPU_PROFILE_DIR_TTL_S         profile-dir GC TTL       (3600)
  RAY_TPU_PROFILE_AUTO              auto-capture enabled     (1)
  RAY_TPU_PROFILE_AUTO_STEPS        steps per auto capture   (3)
  RAY_TPU_PROFILE_AUTO_COOLDOWN_S   min between auto runs    (300)
  RAY_TPU_PROFILE_AUTO_CONSECUTIVE  straggler cuts to arm    (2)
"""

from __future__ import annotations

import logging
import os
import shutil
import sys
import threading
import time

logger = logging.getLogger(__name__)

# Bounds that are invariants, not tunables.
_MAX_STACK_DEPTH = 64
_MAX_FOLDED_KEYS = 50_000
_MAX_ANNOTATIONS = 50_000
_TIMER_GRACE_S = 5.0


def knob_float(name: str, default: float) -> float:
    raw = os.environ.get(f"RAY_TPU_PROFILE_{name}")
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def knob_int(name: str, default: int) -> int:
    return int(knob_float(name, float(default)))


def knob_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(f"RAY_TPU_PROFILE_{name}")
    if raw is None:
        return default
    return raw.lower() in ("1", "true", "yes", "on")


def profiles_base_dir(session_dir: str | None = None) -> str:
    root = session_dir or os.environ.get("RAYTPU_SESSION_DIR") or "/tmp/ray_tpu"
    return os.path.join(root, "profiles")


def gc_profile_dirs(base: str, ttl_s: float | None = None) -> int:
    """Remove profile output dirs older than the TTL (session-scoped GC —
    before this, `rpc_profiler` dirs accumulated forever). Returns the
    number of entries removed; never raises."""
    if ttl_s is None:
        ttl_s = knob_float("DIR_TTL_S", 3600.0)
    removed = 0
    try:
        entries = os.listdir(base)
    except OSError:
        return 0
    cutoff = time.time() - max(0.0, ttl_s)
    for name in entries:
        path = os.path.join(base, name)
        try:
            if os.path.getmtime(path) >= cutoff:
                continue
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.unlink(path)
            removed += 1
        except OSError:
            continue  # raced with another GC / still being written
    return removed


# -- host sampling profiler ----------------------------------------------
class HostSampler:
    """Periodic ``sys._current_frames()`` walk folding stacks in place.

    Robustness contract (satellite: "sampling a thread that exits
    mid-capture cannot crash the worker"):

      * thread names come from a fresh ``threading.enumerate()`` each
        sample — a tid whose Thread object is gone (exited between
        enumerate and the frames snapshot, or tid reused by a brand-new
        native thread) is evicted, never walked with a stale identity
        (mirror of the memory monitor's pid-reuse handle eviction),
      * the frame walk is bounded (depth cap) and exception-guarded —
        a frame torn down mid-walk drops that one sample,
      * the sampler skips its own thread and stops itself after a fork
        (``os.getpid()`` drift) so a forked child never inherits a
        sampling thread ghost.
    """

    def __init__(self, hz: float | None = None):
        self.hz = max(1.0, hz if hz is not None else knob_float("HOST_HZ", 50.0))
        self._interval = 1.0 / self.hz
        self._folded: dict[str, int] = {}
        self._samples = 0
        self._dropped = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._pid = os.getpid()
        self._lock = threading.Lock()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="raytpu-host-sampler", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample_once()
            except Exception:  # rtlint: disable=swallowed-exception - a torn sample must never kill the capture thread
                self._dropped += 1
            self._stop.wait(self._interval)

    def sample_once(self) -> None:
        if os.getpid() != self._pid:
            # Forked child: the cached identity is stale — evict ourselves.
            self._stop.set()
            return
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate() if t.ident}
        frames = sys._current_frames()
        folded_batch: list[str] = []
        for tid, frame in frames.items():
            if tid == me:
                continue
            name = names.get(tid)
            if name is None:
                # Dead-thread / tid-reuse eviction: no live Thread object
                # claims this tid right now — do not walk it.
                continue
            stack: list[str] = []
            try:
                f = frame
                depth = 0
                while f is not None and depth < _MAX_STACK_DEPTH:
                    code = f.f_code
                    stack.append(
                        f"{code.co_name} "
                        f"({os.path.basename(code.co_filename)}:{f.f_lineno})"
                    )
                    f = f.f_back
                    depth += 1
            except Exception:  # rtlint: disable=swallowed-exception - frame freed mid-walk: drop this thread's sample
                self._dropped += 1
                continue
            stack.reverse()
            folded_batch.append(name + ";" + ";".join(stack))
        del frames
        with self._lock:
            self._samples += 1
            for key in folded_batch:
                if key in self._folded:
                    self._folded[key] += 1
                elif len(self._folded) < _MAX_FOLDED_KEYS:
                    self._folded[key] = 1
                else:
                    self._dropped += 1

    def stop(self) -> dict:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        with self._lock:
            return {
                "folded": dict(self._folded),
                "samples": self._samples,
                "dropped": self._dropped,
                "hz": self.hz,
            }


# -- capture plane --------------------------------------------------------
# Module-level fast flags: the per-report boundary hook and the
# per-annotation hooks check ONE bool before touching the plane.
_boundary_armed = False
_capturing = False


class ProfilePlane:
    """Per-worker capture state machine driven by rpc_profiler actions."""

    def __init__(self):
        self._lock = threading.Lock()
        self.state = "idle"  # idle | armed | capturing | done
        self.rank: int | None = None
        self.node_id: str = ""
        self.worker_id: str = ""
        self.last_step: int | None = None
        self._capture_id: str | None = None
        self._start_step: int | None = None
        self._steps = 0
        self._end_step: int | None = None
        self._host = True
        self._device = True
        self._out_dir: str | None = None
        self._sampler: HostSampler | None = None
        self._boundaries: list[dict] = []
        self._annotations: list[dict] = []
        self._phase_totals: dict[str, float] = {}
        self._device_dir: str | None = None
        self._device_error: str | None = None
        self._timer: threading.Timer | None = None
        self._wall_start = 0.0
        self._result: dict | None = None
        self._timed_out = False

    def set_meta(
        self,
        rank: int | None = None,
        node_id: str | None = None,
        worker_id: str | None = None,
    ) -> None:
        if rank is not None:
            self.rank = int(rank)
        if node_id is not None:
            self.node_id = node_id
        if worker_id is not None:
            self.worker_id = worker_id

    # -- control (rpc_profiler actions) ---------------------------------
    def arm(self, payload: dict) -> dict:
        global _boundary_armed
        with self._lock:
            if self.state in ("armed", "capturing"):
                return {
                    "status": "error",
                    "code": "already_active",
                    "error": f"capture {self._capture_id} is {self.state}",
                }
            capture_id = str(payload.get("capture_id") or "manual")
            start_step = payload.get("start_step")
            steps = max(1, int(payload.get("steps") or 1))
            max_s = float(payload.get("max_s") or knob_float("MAX_S", 60.0))
            self._capture_id = capture_id
            self._start_step = (
                int(start_step) if start_step is not None else None
            )
            self._steps = steps
            self._end_step = None
            self._host = bool(payload.get("host", True))
            self._device = bool(payload.get("device", True))
            base = profiles_base_dir(payload.get("session_dir"))
            gc_profile_dirs(base)
            self._out_dir = os.path.join(base, capture_id)
            self._boundaries = []
            self._annotations = []
            self._phase_totals = {}
            self._device_dir = None
            self._device_error = None
            self._result = None
            self._timed_out = False
            self.state = "armed"
            _boundary_armed = True
            # Leak guard: whatever happens to the step stream (loop ends,
            # non-train worker, controller dies), the capture force-stops.
            self._timer = threading.Timer(
                max_s + _TIMER_GRACE_S, self._on_timeout
            )
            self._timer.daemon = True
            self._timer.start()
            if self._start_step is None:
                # No step stream to align on (non-train worker): start now.
                self._begin_locked()
        return {
            "status": "ok",
            "state": self.state,
            "capture_id": self._capture_id,
            "start_step": self._start_step,
        }

    def status(self) -> dict:
        with self._lock:
            return {
                "status": "ok",
                "state": self.state,
                "capture_id": self._capture_id,
                "rank": self.rank,
                "step": self.last_step,
                "start_step": self._start_step,
            }

    def collect(self) -> dict:
        global _boundary_armed
        with self._lock:
            if self.state in ("armed", "capturing"):
                return {
                    "status": "error",
                    "code": "not_done",
                    "error": f"capture {self._capture_id} still {self.state}",
                }
            if self._result is None:
                return {
                    "status": "error",
                    "code": "no_capture",
                    "error": "no completed capture to collect",
                }
            result, self._result = self._result, None
            self.state = "idle"
            _boundary_armed = False
            return {"status": "ok", **result}

    def abort(self) -> dict:
        with self._lock:
            if self.state == "armed":
                self._finish_locked(aborted=True)
                return {"status": "ok", "state": self.state}
            if self.state == "capturing":
                self._stop_locked(aborted=True)
                return {"status": "ok", "state": self.state}
            return {"status": "ok", "state": self.state}

    # -- step-boundary hook (report path) -------------------------------
    def on_step_boundary(self, step: int) -> None:
        with self._lock:
            self.last_step = step
            if self.state == "armed":
                if (
                    self._start_step is not None
                    and step + 1 >= self._start_step
                ):
                    # This boundary is the start edge of step `step+1`.
                    self._begin_locked()
                    self._note_boundary_locked(step)
                return
            if self.state == "capturing":
                self._note_boundary_locked(step)
                if (
                    self._end_step is not None
                    and step >= self._end_step
                ):
                    self._stop_locked()

    # -- annotation hooks (step_annotation / record_phase) --------------
    def note_annotation(self, name: str, wall_start: float, dur_s: float) -> None:
        with self._lock:
            if self.state != "capturing":
                return
            if len(self._annotations) >= _MAX_ANNOTATIONS:
                return
            self._annotations.append(
                {"name": name, "ts": wall_start, "dur_s": dur_s}
            )

    def note_phase(self, phase: str, seconds: float) -> None:
        with self._lock:
            if self.state != "capturing":
                return
            self._phase_totals[phase] = (
                self._phase_totals.get(phase, 0.0) + float(seconds)
            )

    # -- internals (all called with self._lock held) --------------------
    def _begin_locked(self) -> None:
        global _capturing
        self.state = "capturing"
        self._wall_start = time.time()
        first = (
            self.last_step + 1
            if self.last_step is not None
            else (self._start_step or 0)
        )
        self._end_step = first + self._steps - 1
        if self._host:
            self._sampler = HostSampler()
            self._sampler.start()
        if self._device:
            self._start_device_trace_locked()
        _capturing = True

    def _note_boundary_locked(self, step: int) -> None:
        ctx = None
        try:
            from ray_tpu.util import tracing

            ctx = tracing.inject()
        except Exception:  # rtlint: disable=swallowed-exception - trace join is optional enrichment
            pass
        mark = {"step": step, "ts": time.time()}
        if ctx:
            mark["trace_id"] = ctx.get("trace_id")
            mark["span_id"] = ctx.get("span_id")
        self._boundaries.append(mark)

    def _start_device_trace_locked(self) -> None:
        try:
            import jax

            rank = self.rank if self.rank is not None else "x"
            self._device_dir = os.path.join(
                self._out_dir or profiles_base_dir(), f"rank{rank}-device"
            )
            os.makedirs(self._device_dir, exist_ok=True)
            jax.profiler.start_trace(self._device_dir)
        except Exception as exc:  # rtlint: disable=swallowed-exception - device trace is best-effort; host capture proceeds
            self._device_error = str(exc)
            self._device_dir = None

    def _stop_locked(self, aborted: bool = False) -> None:
        global _capturing
        _capturing = False
        host = self._sampler.stop() if self._sampler is not None else None
        self._sampler = None
        if self._device_dir is not None:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as exc:  # rtlint: disable=swallowed-exception - stop after a foreign stop_trace: keep the host capture
                self._device_error = str(exc)
        self._finish_locked(aborted=aborted, host=host)

    def _finish_locked(self, aborted: bool = False, host: dict | None = None) -> None:
        global _boundary_armed
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._result = {
            "capture_id": self._capture_id,
            "rank": self.rank,
            "node_id": self.node_id,
            "worker_id": self.worker_id,
            "wall_start": self._wall_start,
            "wall_end": time.time(),
            "aborted": bool(aborted),
            "timed_out": self._timed_out,
            "boundaries": list(self._boundaries),
            "annotations": list(self._annotations),
            "phase_totals": dict(self._phase_totals),
            "host": host,
            "device_trace_dir": self._device_dir,
            "device_error": self._device_error,
        }
        self.state = "done"
        _boundary_armed = True  # keep hook routing until collect() resets

    def _on_timeout(self) -> None:
        with self._lock:
            self._timed_out = True
            if self.state == "capturing":
                self._stop_locked()
            elif self.state == "armed":
                # Never started (step stream stalled or absent): finish
                # empty so the controller's collect sees a typed record
                # instead of a leaked armed plane.
                self._finish_locked(aborted=True)


_plane: ProfilePlane | None = None
_plane_lock = threading.Lock()


def get_plane() -> ProfilePlane:
    global _plane
    if _plane is None:
        with _plane_lock:
            if _plane is None:
                _plane = ProfilePlane()
    return _plane


# -- hot-path hooks (one module-bool check when idle) ---------------------
def on_step_boundary(step: int) -> None:
    if not _boundary_armed:
        return
    get_plane().on_step_boundary(step)


def note_annotation(name: str, wall_start: float, dur_s: float) -> None:
    if not _capturing:
        return
    get_plane().note_annotation(name, wall_start, dur_s)


def note_phase(phase: str, seconds: float) -> None:
    if not _capturing:
        return
    get_plane().note_phase(phase, seconds)


def capturing() -> bool:
    return _capturing

"""The cluster controller — control plane of the framework.

Role-equivalent of the reference GCS server
(src/ray/gcs/gcs_server/gcs_server.cc [N1]) and its managers:
  * NodeManager      — gcs_node_manager.cc / gcs_health_check_manager.cc [N4]
  * Scheduler        — node selection for leases (HybridSchedulingPolicy,
                       src/ray/raylet/scheduling/scheduling_policy.cc [N10];
                       centralized here rather than per-raylet for v0)
  * ActorManager     — gcs_actor_manager.cc / gcs_actor_scheduler.cc [N2]
  * PlacementGroups  — gcs_placement_group_manager.cc (2-phase commit) [N3]
  * KV               — gcs_kv_manager.cc :: GcsInternalKVManager [N6]
  * PubSub           — src/ray/pubsub/ + gcs_publisher.cc [N8]
  * JobManager       — gcs_job_manager.cc [N5]
  * TaskEvents       — gcs_task_manager.cc (state API feed) [N5]

Runs as its own process (``python -m ray_tpu._private.controller``).
State is in-memory with periodic JSON snapshot persistence to the session
dir and restore-on-restart (the reference's redis_store_client-backed GCS
fault tolerance [N7]/§5.3): agents and workers reconnect with backoff and
re-register, so named/detached actors, PGs, KV and jobs survive a
controller crash.
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import itertools
import json
import os
import sys
import time
from typing import Any

from ray_tpu._private import chaos
from ray_tpu._private.config import global_config
from ray_tpu._private.event_export import EventExporter
from ray_tpu._private.ids import ActorID, PlacementGroupID
from ray_tpu._private.rpc import RpcClient, RpcServer, ServerConnection, spawn_task
from ray_tpu.util import tracing

# Bounded dedup window for mutation idempotency tokens: big enough that a
# client exhausting its chaos/reconnect retry budget is always still inside
# the window, small enough to never matter for memory.
MUTATION_CACHE_SIZE = 4096

ACTOR_STATES = ("PENDING", "ALIVE", "RESTARTING", "DEAD")
PG_STATES = ("PENDING", "CREATED", "REMOVED", "RESCHEDULING")


class _PendingLease:
    """One queued request_lease waiting for capacity, parked in the
    shape-indexed pending queue instead of polling _pick_node."""

    __slots__ = ("future", "resources", "submitter", "strategy", "demand_id")

    def __init__(self, future, resources, submitter, strategy, demand_id):
        self.future = future
        self.resources = resources
        self.submitter = submitter
        self.strategy = strategy
        self.demand_id = demand_id


def _jsonify(obj):
    """JSON-compatible deep copy; bytes become {"__b64__": ...} (actor
    specs carry pickled creation args, KV values are bytes)."""
    import base64

    if isinstance(obj, bytes):
        return {"__b64__": base64.b64encode(obj).decode()}
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    return obj


def _dejsonify(obj):
    import base64

    if isinstance(obj, dict):
        if set(obj) == {"__b64__"}:
            return base64.b64decode(obj["__b64__"])
        return {k: _dejsonify(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_dejsonify(v) for v in obj]
    return obj


class NodeInfo:
    def __init__(self, payload: dict):
        self.node_id: str = payload["node_id"]
        self.agent_addr: tuple = tuple(payload["agent_addr"])
        self.resources_total: dict = dict(payload["resources"])
        self.resources_available: dict = dict(payload["resources"])
        self.store_info: dict = payload["store_info"]
        self.labels: dict = payload.get("labels", {})
        self.last_heartbeat = time.monotonic()
        self.alive = True
        self.client: RpcClient | None = None
        self.stats: dict = {}  # piggybacked heartbeat stats (queue depths)

    def snapshot(self) -> dict:
        return {
            "node_id": self.node_id,
            "agent_addr": list(self.agent_addr),
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "labels": self.labels,
            "alive": self.alive,
            "store_info": self.store_info,
        }


class ActorInfo:
    def __init__(self, spec: dict):
        self.actor_id: str = spec["actor_id"]
        self.spec = spec
        self.state = "PENDING"
        self.address: tuple | None = None
        self.node_id: str | None = None
        self.worker_id: str | None = None
        self.restarts_remaining: int = spec.get("max_restarts", 0)
        self.name: str | None = spec.get("name") or None
        self.detached: bool = spec.get("lifetime") == "detached"
        self.job_id: str = spec.get("job_id", "")
        self.death_cause: str | None = None
        self.ready_event = asyncio.Event()

    def snapshot(self) -> dict:
        return {
            "actor_id": self.actor_id,
            "state": self.state,
            "name": self.name,
            "node_id": self.node_id,
            "pid": self.spec.get("pid"),
            "class_name": self.spec.get("class_name"),
            "job_id": self.job_id,
            "detached": self.detached,
            "restarts_remaining": self.restarts_remaining,
            "death_cause": self.death_cause,
        }


class PlacementGroupInfo:
    def __init__(self, pg_id: str, bundles: list[dict], strategy: str, name: str, job_id: str):
        self.pg_id = pg_id
        self.bundles = bundles              # list of resource dicts
        self.strategy = strategy
        self.name = name
        self.job_id = job_id
        self.state = "PENDING"
        self.bundle_nodes: list[str | None] = [None] * len(bundles)
        self.ready_event = asyncio.Event()

    def snapshot(self) -> dict:
        return {
            "pg_id": self.pg_id,
            "state": self.state,
            "strategy": self.strategy,
            "name": self.name,
            "bundles": self.bundles,
            "bundle_nodes": self.bundle_nodes,
            "job_id": self.job_id,
        }


class Controller:
    def __init__(self, session_dir: str):
        self.session_dir = session_dir
        tracing.configure(session_dir)
        self.server = RpcServer(name="controller")
        self.server.on_disconnect = self._on_disconnect
        self.nodes: dict[str, NodeInfo] = {}
        self.actors: dict[str, ActorInfo] = {}
        self.named_actors: dict[tuple, str] = {}  # (namespace, name) -> actor_id
        self.pgs: dict[str, PlacementGroupInfo] = {}
        self.kv: dict[str, dict[str, bytes]] = collections.defaultdict(dict)
        self.jobs: dict[str, dict] = {}
        self.clients: dict[str, dict] = {}  # worker/driver registry
        self.subscribers: dict[str, set[ServerConnection]] = collections.defaultdict(set)
        self.task_events: collections.deque = collections.deque(
            maxlen=global_config().task_events_max_buffer
        )
        # Queued-but-unplaceable resource demands, for the autoscaler [N4].
        self.pending_demands: dict[str, dict] = {}
        self.events = EventExporter(session_dir)
        # Resource-telemetry time-series store (ISSUE 5): node samples
        # arrive piggybacked on heartbeats and land in bounded, tiered
        # rings (raw → 10s → 60s) — multi-hour runs stay O(MB).
        from ray_tpu._private.telemetry import TelemetryStore

        _cfg = global_config()
        self.telemetry = TelemetryStore(
            raw_capacity=_cfg.telemetry_raw_capacity,
            cap_10s=_cfg.telemetry_10s_capacity,
            cap_60s=_cfg.telemetry_60s_capacity,
        )
        self._rr = itertools.count()
        # --- control-plane scale-out machinery ---
        # Capacity pulse: schedulers park on the CURRENT event; a capacity
        # gain swaps in a fresh event and sets the old one, so waiters wake
        # exactly once per gain with no clear() races.
        self._capacity_event = asyncio.Event()
        # request_lease queue indexed by (resource shape, strategy key):
        # infeasibility is decided once per SHAPE per capacity change, not
        # once per queued request per 200 ms poll. O(1) pop on grant.
        self._pending_leases: dict[tuple, collections.deque] = {}
        self._lease_drain_scheduled = False
        self._demand_seq = itertools.count()
        # Pubsub outbox: events queue per subscriber connection and flush
        # as ONE batched push frame per connection per loop tick instead
        # of one awaited frame per (event x subscriber).
        self._pub_outbox: dict[ServerConnection, list] = {}
        self._pub_flush_scheduled = False
        # Counters the scale suite and /metrics read via controller_stats.
        self.stats_counters = collections.Counter()
        # Comm hang doctor (ISSUE 14): recent watchdog stall events and
        # the merged cluster-wide hang reports built from the evidence
        # harvests they trigger. Bounded: stalls are small dicts, reports
        # carry stacks.
        self._comm_stalls: collections.deque = collections.deque(maxlen=256)
        self._hang_reports: collections.deque = collections.deque(maxlen=8)
        self._hang_harvest_task: asyncio.Task | None = None
        self._last_hang_harvest = 0.0
        # Cluster step profiler (ISSUE 20): completed capture records
        # (small dicts pointing at session-dir artifacts) + the single
        # in-flight capture task. Auto-captures (straggler / comm-stall
        # triggered) are cooldown-guarded here — the controller is the
        # authority, whatever the trigger side rate-limits.
        self._profiles: collections.deque = collections.deque(maxlen=32)
        self._profile_task: asyncio.Task | None = None
        self._last_auto_profile = 0.0
        self._profile_seq = itertools.count()
        # Idempotency-token reply cache for mutation RPCs: a client that
        # retried after a dropped/duplicated reply (or a controller
        # restart) gets the ORIGINAL reply back instead of re-applying
        # the mutation (exactly-once effect over at-least-once delivery).
        # Persisted in the snapshot so dedup survives a restart.
        self._mutation_replies: collections.OrderedDict[str, dict] = (
            collections.OrderedDict()
        )
        chaos.set_identity("controller")
        # Persistence (role-equivalent of the reference's
        # redis_store_client-backed GCS tables [N7]: restart the control
        # plane and the cluster survives). Snapshots are JSON (bytes
        # base64-wrapped) written by _snapshot_loop through a PLUGGABLE
        # store: file (default), memory, or an external wire-v1 KV
        # service (kv://host:port — head-disk loss no longer loses the
        # cluster). Selected via RAY_TPU_controller_store.
        from ray_tpu._private.snapshot_store import make_store

        self.store = make_store(
            global_config().controller_store, session_dir
        )
        print(
            f"[controller] persistence: {self.store.describe()}",
            file=sys.stderr, flush=True,
        )
        self._dirty = False
        # Incremental snapshot state: per-entry serialized JSON fragments
        # for the big tables (actors/pgs/kv) are cached and only dirty
        # keys re-serialize — a 2k-actor table no longer re-encodes in
        # full every snapshot tick (see _build_snapshot_blob).
        self._snap_frag: dict[str, dict] = {"actors": {}, "pgs": {}, "kv": {}}
        self._snap_dirty: dict[str, set] = {
            "actors": set(), "pgs": set(), "kv": set()
        }
        self._snap_all_dirty = True
        self._snap_stats = {
            "saves": 0, "last_bytes": 0, "last_build_ms": 0.0,
            "frags_rebuilt": 0,
        }
        self._restored = self._load_snapshot()

    # ------------------------------------------------------------------
    async def start(self, host: str, port: int) -> int:
        self.server.route_object(self)
        bound = await self.server.start(host, port)
        spawn_task(self._health_check_loop())
        spawn_task(self._snapshot_loop())
        if self._restored:
            spawn_task(self._post_restore_reconcile())
        else:
            for actor in self.actors.values():
                if actor.state in ("PENDING", "RESTARTING"):
                    spawn_task(self._schedule_actor(actor))
            for pg in self.pgs.values():
                if pg.state in ("PENDING", "RESCHEDULING"):
                    spawn_task(self._schedule_pg(pg))
        return bound

    async def _post_restore_reconcile(self) -> None:
        """After a restart: give agents a grace period to re-register (they
        re-attach still-live actors and report their bundle reservations),
        THEN resume interrupted scheduling and fail actors stranded on
        nodes that never came back."""
        cfg = global_config()
        grace = max(
            2.0,
            2 * cfg.health_check_period_ms / 1000.0,
        )
        await asyncio.sleep(grace)
        for actor in list(self.actors.values()):
            if actor.state in ("PENDING", "RESTARTING"):
                spawn_task(self._schedule_actor(actor))
            elif actor.state == "ALIVE" and actor.node_id not in self.nodes:
                # Node never re-registered after the restart window.
                await self._handle_actor_failure(
                    actor, f"node {actor.node_id} lost across controller restart"
                )
        for pg in self.pgs.values():
            if pg.state in ("PENDING", "RESCHEDULING"):
                spawn_task(self._schedule_pg(pg))
            elif pg.state == "CREATED" and any(
                n is not None and n not in self.nodes for n in pg.bundle_nodes
            ):
                pg.state = "RESCHEDULING"
                pg.ready_event.clear()
                for i, nid in enumerate(pg.bundle_nodes):
                    if nid is not None and nid not in self.nodes:
                        pg.bundle_nodes[i] = None
                self._mark_dirty("pgs", pg.pg_id)
                spawn_task(self._schedule_pg(pg))

    # ------------------------------------------------------------------
    # mutation idempotency tokens
    # ------------------------------------------------------------------
    def _mutation_cached(self, payload) -> dict | None:
        token = payload.get("mutation_token") if isinstance(payload, dict) else None
        if token is None:
            return None
        reply = self._mutation_replies.get(token)
        if reply is not None:
            self._mutation_replies.move_to_end(token)
        return reply

    def _mutation_record(self, payload, reply: dict) -> dict:
        token = payload.get("mutation_token") if isinstance(payload, dict) else None
        if token is not None:
            self._mutation_replies[token] = reply
            self._mutation_replies.move_to_end(token)
            while len(self._mutation_replies) > MUTATION_CACHE_SIZE:
                self._mutation_replies.popitem(last=False)
            self._mark_dirty()
        return reply

    # ------------------------------------------------------------------
    # persistence [N7]
    # ------------------------------------------------------------------
    def _mark_dirty(self, section: str | None = None, key=None) -> None:
        """Flag state changed. ``section``/``key`` scope the change to one
        entry of an incrementally-snapshotted table ("actors"/"pgs"/"kv");
        section=None means only the always-fresh small sections (jobs,
        named_actors, mutation cache) moved."""
        self._dirty = True
        if section is not None:
            self._snap_dirty[section].add(key)

    @staticmethod
    def _actor_frag(a: ActorInfo) -> str:
        return json.dumps(_jsonify({
            "spec": a.spec,
            "state": a.state,
            "address": list(a.address) if a.address else None,
            "node_id": a.node_id,
            "worker_id": a.worker_id,
            "restarts_remaining": a.restarts_remaining,
            "death_cause": a.death_cause,
        }))

    @staticmethod
    def _pg_frag(p: PlacementGroupInfo) -> str:
        return json.dumps(_jsonify({
            "bundles": p.bundles,
            "strategy": p.strategy,
            "name": p.name,
            "job_id": p.job_id,
            "state": p.state,
            "bundle_nodes": p.bundle_nodes,
        }))

    def _refresh_snapshot_frags(self) -> int:
        """Bring the cached per-entry fragments up to date; returns how
        many fragments were re-serialized this pass."""
        frags = self._snap_frag
        dirty = self._snap_dirty
        rebuilt = 0
        if self._snap_all_dirty:
            self._snap_all_dirty = False
            for s in dirty.values():
                s.clear()
            frags["actors"] = {
                aid: self._actor_frag(a) for aid, a in self.actors.items()
            }
            frags["pgs"] = {
                pid: self._pg_frag(p) for pid, p in self.pgs.items()
            }
            frags["kv"] = {
                (ns, k): json.dumps(_jsonify([ns, k, v]))
                for ns, kvs in self.kv.items()
                for k, v in kvs.items()
            }
            return (
                len(frags["actors"]) + len(frags["pgs"]) + len(frags["kv"])
            )
        for aid in dirty["actors"]:
            a = self.actors.get(aid)
            if a is None:
                frags["actors"].pop(aid, None)
            else:
                frags["actors"][aid] = self._actor_frag(a)
                rebuilt += 1
        for pid in dirty["pgs"]:
            p = self.pgs.get(pid)
            if p is None:
                frags["pgs"].pop(pid, None)
            else:
                frags["pgs"][pid] = self._pg_frag(p)
                rebuilt += 1
        for ns_key in dirty["kv"]:
            ns, k = ns_key
            v = self.kv.get(ns, {}).get(k)
            if v is None:
                frags["kv"].pop(ns_key, None)
            else:
                frags["kv"][ns_key] = json.dumps(_jsonify([ns, k, v]))
                rebuilt += 1
        for s in dirty.values():
            s.clear()
        return rebuilt

    def _build_snapshot_blob(self) -> bytes:
        """Runs ON the event loop: the state walk must be atomic w.r.t.
        handlers mutating actors/pgs/kv — only the (pure) store write is
        pushed to a worker thread. Incremental: the big tables assemble
        from cached per-entry fragments (only dirty keys re-serialize);
        the small sections (jobs, named actors, mutation-token cache) are
        serialized fresh each build."""
        start = time.perf_counter()
        rebuilt = self._refresh_snapshot_frags()
        frags = self._snap_frag
        parts = [
            '"actors":{'
            + ",".join(
                f"{json.dumps(aid)}:{frag}"
                for aid, frag in frags["actors"].items()
            )
            + "}",
            '"pgs":{'
            + ",".join(
                f"{json.dumps(pid)}:{frag}"
                for pid, frag in frags["pgs"].items()
            )
            + "}",
            '"kv_flat":[' + ",".join(frags["kv"].values()) + "]",
            '"named_actors":'
            + json.dumps([
                [ns, name, aid]
                for (ns, name), aid in self.named_actors.items()
            ]),
            '"jobs":' + json.dumps(_jsonify(self.jobs)),
            # Token cache rides along so mutation dedup spans restarts: a
            # client retrying across a controller crash still gets its
            # original reply, not a re-application.
            '"mutations":'
            + json.dumps(_jsonify(list(self._mutation_replies.items()))),
        ]
        blob = ("{" + ",".join(parts) + "}").encode()
        self._snap_stats["last_bytes"] = len(blob)
        self._snap_stats["last_build_ms"] = (
            (time.perf_counter() - start) * 1000.0
        )
        self._snap_stats["frags_rebuilt"] = rebuilt
        self._snap_stats["saves"] += 1
        return blob

    def _load_snapshot(self) -> bool:
        blob = None
        last_exc = None
        for attempt in range(5):
            try:
                blob = self.store.load()
                last_exc = None
                break
            except Exception as exc:
                last_exc = exc
                time.sleep(0.5 * (attempt + 1))
        if last_exc is not None:
            # An UNREACHABLE store is not the same as an EMPTY one:
            # booting fresh would later overwrite the good external
            # snapshot with empty state. Fail the boot; the operator (or
            # supervisor restart loop) retries once the store is back.
            raise RuntimeError(
                f"snapshot store {self.store.describe()} unreachable at "
                f"boot: {last_exc}"
            )
        if blob is None:
            return False
        try:
            state = _dejsonify(json.loads(blob))
        except Exception as exc:
            print(
                f"[controller] snapshot load failed: {exc}",
                file=sys.stderr, flush=True,
            )
            return False
        for aid, rec in state.get("actors", {}).items():
            actor = ActorInfo(rec["spec"])
            actor.state = rec["state"]
            actor.address = tuple(rec["address"]) if rec["address"] else None
            actor.node_id = rec["node_id"]
            actor.worker_id = rec["worker_id"]
            actor.restarts_remaining = rec["restarts_remaining"]
            actor.death_cause = rec["death_cause"]
            if actor.state in ("ALIVE", "DEAD"):
                actor.ready_event.set()
            self.actors[aid] = actor
        for ns, name, aid in state.get("named_actors", []):
            self.named_actors[(ns, name)] = aid
        for pid, rec in state.get("pgs", {}).items():
            pg = PlacementGroupInfo(
                pid, rec["bundles"], rec["strategy"], rec["name"], rec["job_id"]
            )
            pg.state = rec["state"]
            pg.bundle_nodes = rec["bundle_nodes"]
            if pg.state == "CREATED":
                pg.ready_event.set()
            self.pgs[pid] = pg
        for ns, kvs in state.get("kv", {}).items():  # legacy nested format
            self.kv[ns].update(kvs)
        for ns, k, v in state.get("kv_flat", []):
            self.kv[ns][k] = v
        self.jobs.update(state.get("jobs", {}))
        for token, reply in state.get("mutations", []):
            self._mutation_replies[token] = reply
        print(
            f"[controller] restored snapshot: {len(self.actors)} actors, "
            f"{len(self.pgs)} pgs, {sum(len(v) for v in self.kv.values())} kv keys",
            file=sys.stderr, flush=True,
        )
        return True

    async def _snapshot_loop(self) -> None:
        period = global_config().controller_snapshot_period_s
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(period)
            if not self._dirty:
                continue
            self._dirty = False
            try:
                # Chaos probe for the dirty-bit retry path below: an armed
                # "controller.snapshot_save" fail-point loses the write
                # exactly like a store outage between write and ack would.
                chaos.failpoint("controller.snapshot_save")
                blob = self._build_snapshot_blob()  # on-loop: consistent
                # executor: an external store's socket write must not
                # stall the control plane's event loop.
                await loop.run_in_executor(None, self.store.timed_save, blob)
            except Exception as exc:
                self._dirty = True  # retry next tick; don't lose the state
                print(
                    f"[controller] snapshot write failed: {exc}",
                    file=sys.stderr, flush=True,
                )

    async def _node_client(self, node: NodeInfo) -> RpcClient:
        if node.client is None or not node.client.connected:
            node.client = RpcClient(node.agent_addr, name=f"to-agent-{node.node_id[:10]}")
            node.client.chaos_peer = f"node:{node.node_id}"
            await node.client.connect()
        return node.client

    # ------------------------------------------------------------------
    # pubsub [N8]
    # ------------------------------------------------------------------
    async def rpc_subscribe(self, conn: ServerConnection, payload) -> dict:
        for channel in payload["channels"]:
            self.subscribers[channel].add(conn)
        conn.context.setdefault("subscriptions", set()).update(payload["channels"])
        return {"status": "ok"}

    async def publish(self, channel: str, message: Any) -> None:
        # Every lifecycle broadcast also lands in the structured export
        # files (event.cc/N28 role): pubsub reaches connected subscribers,
        # the export reaches external consumers after the fact.
        self.events.emit(channel, message)
        subs = self.subscribers.get(channel)
        if not subs:
            return
        # Queue per connection; one batched push frame per connection per
        # loop tick (a 2k-event burst costs each subscriber one frame, not
        # 2k awaited sends serialized through the handler).
        dead = []
        for conn in subs:
            if conn.closed.is_set():
                dead.append(conn)
                continue
            self._pub_outbox.setdefault(conn, []).append((channel, message))
        for conn in dead:
            subs.discard(conn)
        if self._pub_outbox and not self._pub_flush_scheduled:
            self._pub_flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._spawn_pub_flush)

    def _spawn_pub_flush(self) -> None:
        spawn_task(self._flush_pubsub())

    async def _flush_pubsub(self) -> None:
        self._pub_flush_scheduled = False
        outbox = self._pub_outbox
        if not outbox:
            return
        self._pub_outbox = {}
        for conn, items in outbox.items():
            if conn.closed.is_set():
                continue
            self.stats_counters["pubsub_frames"] += 1
            self.stats_counters["pubsub_events"] += len(items)
            try:
                if len(items) == 1:
                    await conn.push(items[0][0], items[0][1])
                else:
                    # Client-side demux in rpc._ClientCallMixin._handle_push.
                    await conn.push(
                        "__pub_batch__", [[c, m] for c, m in items]
                    )
            except Exception:  # rtlint: disable=swallowed-exception - dead subscriber conn; pruned on disconnect
                pass

    async def rpc_publish(self, conn, payload) -> dict:
        await self.publish(payload["channel"], payload["message"])
        return {"status": "ok"}

    # ------------------------------------------------------------------
    # capacity wakeups (event-driven scheduling, no poll loops)
    # ------------------------------------------------------------------
    def _notify_capacity(self) -> None:
        """Cluster capacity may have grown (node registered, heartbeat
        reported freed resources, PG became placeable). Pulse the parked
        schedulers and drain the shape-indexed pending-lease queue —
        coalesced to one drain per loop tick however many notifications
        land in a burst."""
        event = self._capacity_event
        self._capacity_event = asyncio.Event()
        event.set()
        if self._pending_leases and not self._lease_drain_scheduled:
            self._lease_drain_scheduled = True
            asyncio.get_running_loop().call_soon(self._drain_pending_leases)

    async def _wait_for_capacity(self, timeout: float) -> None:
        """Park until the next capacity pulse (or timeout as a safety
        net). Grab the event BEFORE awaiting: a pulse between the check
        and the wait must not be lost."""
        event = self._capacity_event
        try:
            await asyncio.wait_for(event.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    # ------------------------------------------------------------------
    # node management [N4] + health checks
    # ------------------------------------------------------------------
    async def rpc_register_node(self, conn: ServerConnection, payload) -> dict:
        node = NodeInfo(payload)
        self.nodes[node.node_id] = node
        conn.context["node_id"] = node.node_id
        # Post-restart reconciliation: the agent reports the actors it
        # still hosts. Restored ALIVE actors missing from the report died
        # while the controller was down; reported actors whose snapshot
        # predates their ALIVE transition are re-attached in place (never
        # double-scheduled).
        live_entries = payload.get("live_actors") or []
        live = {e["actor_id"] if isinstance(e, dict) else e for e in live_entries}
        # Ghost workers: a partitioned-then-healed node re-registers still
        # hosting actors the controller failed over in the meantime (DEAD,
        # or ALIVE again on a DIFFERENT node). Tell the agent so it kills
        # them instead of serving two incarnations of one actor.
        stale_actors: list[dict] = []
        for entry in live_entries:
            if not isinstance(entry, dict):
                continue
            actor = self.actors.get(entry["actor_id"])
            if actor is not None and actor.state in ("PENDING", "RESTARTING"):
                actor.node_id = node.node_id
                actor.worker_id = entry.get("worker_id")
                if entry.get("addr"):
                    actor.address = tuple(entry["addr"])
                actor.state = "ALIVE"
                actor.ready_event.set()
                self._mark_dirty("actors", actor.actor_id)
            elif actor is None or actor.state == "DEAD" or (
                actor.state == "ALIVE" and actor.node_id != node.node_id
            ):
                stale_actors.append(
                    {"actor_id": entry["actor_id"],
                     "worker_id": entry.get("worker_id")}
                )
        for actor in list(self.actors.values()):
            if (
                actor.node_id == node.node_id
                and actor.state == "ALIVE"
                and actor.actor_id not in live
            ):
                await self._handle_actor_failure(
                    actor, "worker died during controller restart"
                )
        # Release phase-1 bundle reservations the agent still holds for
        # placement groups this incarnation no longer accounts to it
        # (2PC prepare leaked across a controller crash).
        stale: list[int | str] = []
        for entry in payload.get("held_bundles") or []:
            pg_id, index = entry["pg_id"], entry["index"]
            pg = self.pgs.get(pg_id)
            if (
                pg is None
                or pg.state == "REMOVED"
                or index >= len(pg.bundle_nodes)
                or pg.bundle_nodes[index] != node.node_id
            ):
                stale.append(entry)
        if stale:
            spawn_task(self._release_stale_bundles(node, stale))
        await self.publish("node_added", node.snapshot())
        self._notify_capacity()
        await self._retry_pending()
        return {"status": "ok", "stale_actors": stale_actors}

    async def _release_stale_bundles(self, node: NodeInfo, stale: list) -> None:
        try:
            client = await self._node_client(node)
            for entry in stale:
                await client.call(
                    "release_bundle",
                    {"pg_id": entry["pg_id"], "bundle_index": entry["index"]},
                )
        except Exception:  # rtlint: disable=swallowed-exception - node unreachable: its death frees the bundles anyway
            pass

    async def rpc_heartbeat(self, conn, payload) -> dict:
        node = self.nodes.get(payload["node_id"])
        if node is None:
            return {"status": "unknown_node"}
        if not node.alive:
            # The node was declared dead (partition outlasted the health
            # timeout): its actors were failed over and its PG bundles
            # rescheduled. Silently flipping alive=True here would leave
            # it half-dead — carrying workers the controller no longer
            # accounts to it and missing everything scheduled since.
            # Make it re-register: the register path reconciles live
            # actors/bundles and tells the agent which workers are stale.
            return {"status": "reregister"}
        node.last_heartbeat = time.monotonic()
        prev = node.resources_available
        fresh = payload["resources_available"]
        node.resources_available = fresh
        if payload.get("stats") is not None:
            # Agents piggyback queue-depth/engine counters on the
            # heartbeat they already send — no extra stats RPC fan-in.
            node.stats = payload["stats"]
        if payload.get("telemetry"):
            # Resource samples ride the same beat; the store's monotonic
            # guard drops chaos-duplicated/replayed samples.
            self.telemetry.add_many(node.node_id, payload["telemetry"])
        self.stats_counters["heartbeats"] += 1
        # Wake parked schedulers only on a capacity GAIN: a steady-state
        # heartbeat from each of N nodes per tick must not trigger N
        # rescheduling sweeps.
        for key, value in fresh.items():
            if value > prev.get(key, 0.0) + 1e-9:
                self._notify_capacity()
                break
        return {"status": "ok"}

    async def _health_check_loop(self) -> None:
        cfg = global_config()
        period = cfg.health_check_period_ms / 1000.0
        timeout = (
            cfg.health_check_timeout_ms * cfg.health_check_failure_threshold / 1000.0
        )
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for node in list(self.nodes.values()):
                if node.alive and now - node.last_heartbeat > timeout:
                    await self._on_node_death(node)
            # Safety-net drain: pending leases are normally woken by
            # capacity pulses; this sweep bounds the wait if a pulse was
            # missed (e.g. a heartbeat-less test mutates node state).
            if self._pending_leases and not self._lease_drain_scheduled:
                self._lease_drain_scheduled = True
                asyncio.get_running_loop().call_soon(
                    self._drain_pending_leases
                )

    async def _on_node_death(self, node: NodeInfo) -> None:
        node.alive = False
        await self.publish("node_removed", {"node_id": node.node_id})
        # Fail actors on the node; restart the restartable ones.
        for actor in list(self.actors.values()):
            if actor.node_id == node.node_id and actor.state in ("ALIVE", "PENDING"):
                await self._handle_actor_failure(actor, f"node {node.node_id} died")
        # Reschedule placement-group bundles that lived there.
        for pg in self.pgs.values():
            if pg.state == "CREATED" and node.node_id in pg.bundle_nodes:
                pg.state = "RESCHEDULING"
                pg.ready_event.clear()
                for i, nid in enumerate(pg.bundle_nodes):
                    if nid == node.node_id:
                        pg.bundle_nodes[i] = None
                self._mark_dirty("pgs", pg.pg_id)
                spawn_task(self._schedule_pg(pg))

    async def _on_disconnect(self, conn: ServerConnection) -> None:
        node_id = conn.context.get("node_id")
        if node_id and node_id in self.nodes:
            node = self.nodes[node_id]
            if node.alive:
                await self._on_node_death(node)
        client_id = conn.context.get("client_id")
        if client_id:
            info = self.clients.pop(client_id, None)
            if info and info.get("is_driver"):
                await self._on_driver_exit(info["job_id"])
        for channel in conn.context.get("subscriptions", ()):
            self.subscribers[channel].discard(conn)

    # ------------------------------------------------------------------
    # clients / jobs [N5]
    # ------------------------------------------------------------------
    async def rpc_register_client(self, conn: ServerConnection, payload) -> dict:
        self.clients[payload["worker_id"]] = payload
        conn.context["client_id"] = payload["worker_id"]
        if payload.get("is_driver"):
            job_id = payload["job_id"]
            self.jobs.setdefault(
                job_id,
                {
                    "job_id": job_id,
                    "driver_id": payload["worker_id"],
                    "start_time": time.time(),
                    "state": "RUNNING",
                },
            )
            self.events.emit("job_started", {"job_id": job_id})
            self._mark_dirty()
        return {"status": "ok"}

    async def _on_driver_exit(self, job_id: str) -> None:
        job = self.jobs.get(job_id)
        if job:
            job["state"] = "FINISHED"
            job["end_time"] = time.time()
            self._mark_dirty()
        # Kill non-detached actors of the job.
        for actor in list(self.actors.values()):
            if actor.job_id == job_id and not actor.detached and actor.state != "DEAD":
                await self._kill_actor(actor, "driver exited", no_restart=True)
        # Remove the job's placement groups.
        for pg in list(self.pgs.values()):
            if pg.job_id == job_id and pg.state != "REMOVED":
                await self._remove_pg(pg)
        await self.publish("job_finished", {"job_id": job_id})

    async def rpc_list_jobs(self, conn, payload) -> list:
        return list(self.jobs.values())

    # ------------------------------------------------------------------
    # KV [N6]
    # ------------------------------------------------------------------
    async def rpc_kv_put(self, conn, payload) -> dict:
        # Without a token, a retried overwrite=False put whose first reply
        # was dropped comes back "exists" — the caller can't tell its own
        # earlier write from a genuine conflict. The cache returns the
        # original "ok" instead.
        cached = self._mutation_cached(payload)
        if cached is not None:
            return cached
        ns = payload.get("namespace", "default")
        overwrite = payload.get("overwrite", True)
        if not overwrite and payload["key"] in self.kv[ns]:
            return self._mutation_record(payload, {"status": "exists"})
        self.kv[ns][payload["key"]] = payload["value"]
        self._mark_dirty("kv", (ns, payload["key"]))
        return self._mutation_record(payload, {"status": "ok"})

    async def rpc_kv_multi_put(self, conn, payload) -> dict:
        """Batched kv_put: one RPC carries many entries (the metrics
        flusher sends its whole tick in one call). Idempotent as a unit
        via the same mutation-token cache as kv_put."""
        cached = self._mutation_cached(payload)
        if cached is not None:
            return cached
        ns = payload.get("namespace", "default")
        overwrite = payload.get("overwrite", True)
        statuses = []
        for entry in payload.get("entries", ()):
            key = entry["key"]
            if not overwrite and key in self.kv[ns]:
                statuses.append("exists")
                continue
            self.kv[ns][key] = entry["value"]
            self._mark_dirty("kv", (ns, key))
            statuses.append("ok")
        return self._mutation_record(
            payload, {"status": "ok", "statuses": statuses}
        )

    async def rpc_kv_get(self, conn, payload) -> dict:
        ns = payload.get("namespace", "default")
        value = self.kv[ns].get(payload["key"])
        return {"status": "ok" if value is not None else "missing", "value": value}

    async def rpc_kv_del(self, conn, payload) -> dict:
        cached = self._mutation_cached(payload)
        if cached is not None:
            return cached
        ns = payload.get("namespace", "default")
        existed = self.kv[ns].pop(payload["key"], None) is not None
        if existed:
            self._mark_dirty("kv", (ns, payload["key"]))
        return self._mutation_record(
            payload, {"status": "ok", "existed": existed}
        )

    async def rpc_kv_keys(self, conn, payload) -> list:
        ns = payload.get("namespace", "default")
        prefix = payload.get("prefix", "")
        return [k for k in self.kv[ns] if k.startswith(prefix)]

    # ------------------------------------------------------------------
    # lease scheduling (HybridSchedulingPolicy-flavored) [N10]
    # ------------------------------------------------------------------
    def _fits(self, node: NodeInfo, resources: dict) -> bool:
        for key, need in resources.items():
            if need <= 0:
                continue
            if node.resources_available.get(key, 0.0) + 1e-9 < need:
                return False
        return True

    def _fits_total(self, node: NodeInfo, resources: dict) -> bool:
        return all(
            node.resources_total.get(k, 0.0) + 1e-9 >= v
            for k, v in resources.items()
            if v > 0
        )

    def _utilization(self, node: NodeInfo) -> float:
        # Allocation-free max: this runs per (node x scheduling decision)
        # and shows up first in 32-node profiles.
        best = 0.0
        available = node.resources_available
        for key, total in node.resources_total.items():
            if total > 0:
                frac = (total - available.get(key, 0.0)) / total
                if frac > best:
                    best = frac
        return best

    def _pick_node(self, resources: dict, submitter_node: str | None, strategy: dict) -> NodeInfo | None:
        alive = [n for n in self.nodes.values() if n.alive]
        if not alive:
            return None
        kind = strategy.get("kind", "")
        if kind == "pg":
            pg = self.pgs.get(strategy["pg_id"])
            if pg is None or pg.state != "CREATED":
                return None
            index = strategy.get("bundle_index", -1)
            candidates = (
                [pg.bundle_nodes[index]]
                if index >= 0
                else [n for n in pg.bundle_nodes]
            )
            for node_id in candidates:
                node = self.nodes.get(node_id or "")
                if node and node.alive:
                    return node
            return None
        if kind == "node_affinity":
            node = self.nodes.get(strategy["node_id"])
            if node and node.alive and self._fits(node, resources):
                return node
            if strategy.get("soft"):
                pass  # fall through to default policy
            else:
                return None
        if kind == "SPREAD":
            feasible = [n for n in alive if self._fits(n, resources)]
            if not feasible:
                feasible = [n for n in alive if self._fits_total(n, resources)]
            if not feasible:
                return None
            return feasible[next(self._rr) % len(feasible)]
        # Hybrid policy: prefer the submitter's node while its utilization is
        # below the spread threshold, else best-fit across the cluster
        # (scheduling_policy.cc :: HybridSchedulingPolicy).
        threshold = global_config().scheduler_spread_threshold
        local = self.nodes.get(submitter_node or "")
        if (
            local is not None
            and local.alive
            and self._fits(local, resources)
            and self._utilization(local) < threshold
        ):
            return local
        feasible = [n for n in alive if self._fits(n, resources)]
        if feasible:
            return min(feasible, key=self._utilization)
        feasible_total = [n for n in alive if self._fits_total(n, resources)]
        if feasible_total:
            return min(feasible_total, key=self._utilization)
        return None

    async def rpc_get_load(self, conn, payload) -> dict:
        """Aggregated resource load for the autoscaler (reference:
        gcs_resource_manager.cc resource load reports → autoscaler)."""
        return {
            "pending_demands": list(self.pending_demands.values()),
            # Unplaced placement groups (autoscaler v2 input: a pending
            # pod-slice PG is THE TPU-native scale-up signal — slices are
            # allocated whole, not host by host).
            "pending_pgs": [
                {
                    "pg_id": pid,
                    "strategy": pg.strategy,
                    "bundles": pg.bundles,
                }
                for pid, pg in self.pgs.items()
                if pg.state in ("PENDING", "RESCHEDULING")
            ],
            "nodes": [
                {
                    "node_id": n.node_id,
                    "alive": n.alive,
                    "resources_total": n.resources_total,
                    "resources_available": n.resources_available,
                }
                for n in self.nodes.values()
            ],
        }

    @staticmethod
    def _lease_shape(resources: dict, strategy: dict) -> tuple:
        """Canonical queue key: requests with equal shape+strategy are
        feasibility-equivalent, so one _pick_node probe decides for the
        whole bucket."""
        kind = strategy.get("kind", "")
        if kind == "pg":
            extra = ("pg", strategy["pg_id"], strategy.get("bundle_index", -1))
        elif kind == "node_affinity":
            extra = ("node", strategy["node_id"], bool(strategy.get("soft")))
        elif kind:
            extra = (kind,)
        else:
            extra = ()
        return (
            tuple(sorted(
                (k, float(v)) for k, v in resources.items() if v > 0
            )),
            extra,
        )

    def _drain_pending_leases(self) -> None:
        """One pass over the pending-lease queue, run as a loop callback
        after a capacity gain. Per SHAPE: one infeasibility probe rejects
        the whole bucket in O(1); feasible buckets pop waiters until the
        shape stops fitting."""
        self._lease_drain_scheduled = False
        if not self._pending_leases:
            return
        for shape in list(self._pending_leases):
            waiters = self._pending_leases.get(shape)
            while waiters:
                req = waiters[0]
                if req.future.done():  # timed out / disconnected
                    waiters.popleft()
                    continue
                node = self._pick_node(req.resources, req.submitter,
                                       req.strategy)
                if node is None:
                    break  # shape still infeasible: bucket stays parked
                waiters.popleft()
                self.pending_demands.pop(req.demand_id, None)
                self.stats_counters["lease_queue_grants"] += 1
                req.future.set_result(node)
            if not waiters:
                self._pending_leases.pop(shape, None)

    async def _queue_lease_request(
        self, resources: dict, submitter: str | None, strategy: dict,
        timeout: float,
    ) -> NodeInfo | None:
        """Park an unplaceable lease request until capacity shows up (the
        reference queues in raylets; we queue here). Queued demand stays
        visible to the autoscaler via pending_demands."""
        demand_id = f"lease-{next(self._demand_seq)}"
        future = asyncio.get_running_loop().create_future()
        req = _PendingLease(future, resources, submitter, strategy, demand_id)
        shape = self._lease_shape(resources, strategy)
        self._pending_leases.setdefault(shape, collections.deque()).append(req)
        self.pending_demands[demand_id] = dict(resources)
        self.stats_counters["lease_queue_enqueued"] += 1
        try:
            return await asyncio.wait_for(future, timeout)
        except asyncio.TimeoutError:
            return None
        finally:
            self.pending_demands.pop(demand_id, None)

    async def rpc_request_lease(self, conn, payload) -> dict:
        resources = payload["resources"]
        strategy = payload.get("scheduling_strategy") or {}
        self.stats_counters["lease_requests"] += 1
        trace_ctx = payload.get("trace_ctx") if tracing.enabled() else None
        wait_start_ns = time.time_ns() if trace_ctx else 0
        parked = False
        node = self._pick_node(
            resources, payload.get("submitter_node"), strategy
        )
        if node is None:
            parked = True
            node = await self._queue_lease_request(
                resources, payload.get("submitter_node"), strategy,
                timeout=60.0,
            )
        if trace_ctx:
            # Parked-queue time as seen by the scheduler: ~0 when capacity
            # was immediately available, the full park otherwise.
            tracing.emit(
                "lease_wait", trace_ctx, start_ns=wait_start_ns,
                status="ok" if node is not None else "error",
                parked=parked,
                resources={k: v for k, v in resources.items() if v},
            )
        if node is None:
            return {"status": "infeasible"}
        bundle = None
        if strategy.get("kind") == "pg":
            bundle = {
                "pg_id": strategy["pg_id"],
                "bundle_index": strategy.get("bundle_index", -1),
            }
        return {
            "status": "ok",
            "node_id": node.node_id,
            "agent_addr": list(node.agent_addr),
            "bundle": bundle,
        }

    async def _retry_pending(self) -> None:
        for pg in list(self.pgs.values()):
            if pg.state in ("PENDING", "RESCHEDULING"):
                spawn_task(self._schedule_pg(pg))

    # ------------------------------------------------------------------
    # actors [N2]
    # ------------------------------------------------------------------
    async def rpc_create_actor(self, conn, payload) -> dict:
        spec = payload
        # Idempotent twice over: the mutation token catches any re-send
        # (dropped/duplicated reply, reconnect replay) without touching
        # state, and the actor_id check backstops token-less callers —
        # either way a duplicate never double-schedules.
        cached = self._mutation_cached(payload)
        if cached is not None:
            return cached
        existing = self.actors.get(spec["actor_id"])
        if existing is not None:
            return self._mutation_record(
                payload, {"status": "ok", "actor_id": existing.actor_id}
            )
        actor = ActorInfo(spec)
        if actor.name:
            key = (spec.get("namespace", "default"), actor.name)
            if key in self.named_actors:
                return self._mutation_record(
                    payload,
                    {"status": "name_exists",
                     "actor_id": self.named_actors[key]},
                )
            self.named_actors[key] = actor.actor_id
        self.actors[actor.actor_id] = actor
        self._mark_dirty("actors", actor.actor_id)
        spawn_task(self._schedule_actor(actor))
        return self._mutation_record(
            payload, {"status": "ok", "actor_id": actor.actor_id}
        )

    @staticmethod
    def _debit(node: NodeInfo, resources: dict) -> None:
        """Optimistic local reservation: decrement the controller's VIEW of
        a node's availability the moment a placement is chosen, so a burst
        of concurrent _schedule_* coroutines spreads across the cluster
        instead of thundering onto the node the last heartbeat said was
        emptiest. The next heartbeat overwrites with the agent's
        authoritative value, so drift self-heals within one tick."""
        avail = node.resources_available
        for k, v in resources.items():
            if v > 0:
                avail[k] = avail.get(k, 0.0) - v

    @staticmethod
    def _credit(node: NodeInfo, resources: dict) -> None:
        avail = node.resources_available
        for k, v in resources.items():
            if v > 0:
                avail[k] = avail.get(k, 0.0) + v

    async def _schedule_actor(self, actor: ActorInfo) -> None:
        spec = actor.spec
        deadline = time.monotonic() + 120.0
        while True:
            resources = spec.get("resources", {"CPU": 1})
            node = self._pick_node(
                resources,
                spec.get("submitter_node"),
                spec.get("scheduling_strategy") or {},
            )
            if node is not None:
                self._debit(node, resources)
                started = False
                try:
                    client = await self._node_client(node)
                    resp = await client.call(
                        "start_actor",
                        {
                            "actor_id": actor.actor_id,
                            "spec": {
                                k: v
                                for k, v in spec.items()
                                if k not in ("creation_args",)
                            },
                            "creation_args": spec.get("creation_args"),
                        },
                    )
                    if resp["status"] == "ok":
                        started = True
                        actor.node_id = node.node_id
                        actor.worker_id = resp["worker_id"]
                        actor.spec["pid"] = resp.get("pid")
                        actor.address = tuple(resp["worker_addr"])
                        actor.state = "ALIVE"
                        actor.ready_event.set()
                        self._mark_dirty("actors", actor.actor_id)
                        await self.publish("actor_state", actor.snapshot())
                        return
                    print(
                        f"[controller] start_actor {actor.actor_id[:12]} on "
                        f"{node.node_id[:12]}: {resp}",
                        file=sys.stderr, flush=True,
                    )
                except Exception as exc:
                    print(
                        f"[controller] start_actor {actor.actor_id[:12]} "
                        f"error: {type(exc).__name__}: {exc}",
                        file=sys.stderr, flush=True,
                    )
                finally:
                    if not started:
                        self._credit(node, resources)
            if time.monotonic() > deadline:
                actor.state = "DEAD"
                actor.death_cause = "unschedulable: no feasible node"
                actor.ready_event.set()
                self._mark_dirty("actors", actor.actor_id)
                await self.publish("actor_state", actor.snapshot())
                return
            # Event-driven retry: woken by the next capacity gain (node
            # added, resources freed) instead of a fixed 200 ms poll.
            await self._wait_for_capacity(1.0)

    async def _handle_actor_failure(self, actor: ActorInfo, cause: str) -> None:
        if actor.state == "DEAD":
            return
        if actor.restarts_remaining != 0:
            if actor.restarts_remaining > 0:
                actor.restarts_remaining -= 1
            actor.state = "RESTARTING"
            actor.address = None
            actor.ready_event.clear()
            self._mark_dirty("actors", actor.actor_id)
            await self.publish("actor_state", actor.snapshot())
            spawn_task(self._schedule_actor(actor))
        else:
            actor.state = "DEAD"
            actor.death_cause = cause
            actor.ready_event.set()
            if actor.name:
                self.named_actors.pop(
                    (actor.spec.get("namespace", "default"), actor.name), None
                )
            self._mark_dirty("actors", actor.actor_id)
            await self.publish("actor_state", actor.snapshot())

    async def rpc_worker_died(self, conn, payload) -> dict:
        """Reported by a node agent when a worker process exits."""
        actor_id = payload.get("actor_id")
        if actor_id and actor_id in self.actors:
            actor = self.actors[actor_id]
            if payload.get("intended") or actor.state == "DEAD":
                pass
            else:
                if payload.get("reason") == "oom":
                    cause = (
                        "worker killed by the node memory monitor (OOM, "
                        f"exit={payload.get('exit_code')})"
                    )
                else:
                    cause = (
                        f"worker process died (exit={payload.get('exit_code')})"
                    )
                await self._handle_actor_failure(actor, cause)
        return {"status": "ok"}

    async def rpc_get_actor_info(self, conn, payload) -> dict:
        actor = self.actors.get(payload["actor_id"])
        if actor is None:
            return {"state": "UNKNOWN"}
        if payload.get("wait_ready"):
            await actor.ready_event.wait()
        return {
            "state": actor.state,
            "address": list(actor.address) if actor.address else None,
            "node_id": actor.node_id,
            "death_cause": actor.death_cause,
        }

    async def rpc_get_named_actor(self, conn, payload) -> dict:
        key = (payload.get("namespace", "default"), payload["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None:
            return {"status": "missing"}
        actor = self.actors[actor_id]
        return {
            "status": "ok",
            "actor_id": actor_id,
            "spec_meta": {
                "class_name": actor.spec.get("class_name"),
                "methods": actor.spec.get("methods", []),
                "max_task_retries": actor.spec.get("max_task_retries", 0),
            },
        }

    async def rpc_kill_actor(self, conn, payload) -> dict:
        actor = self.actors.get(payload["actor_id"])
        if actor is None:
            return {"status": "missing"}
        await self._kill_actor(
            actor, "ray_tpu.kill", no_restart=payload.get("no_restart", True)
        )
        return {"status": "ok"}

    async def _kill_actor(self, actor: ActorInfo, cause: str, no_restart: bool) -> None:
        if no_restart:
            actor.restarts_remaining = 0
        node = self.nodes.get(actor.node_id or "")
        if node is not None and node.alive and actor.worker_id:
            try:
                client = await self._node_client(node)
                await client.call(
                    "kill_worker",
                    {"worker_id": actor.worker_id, "actor_id": actor.actor_id,
                     "intended": no_restart},
                )
            except Exception:  # rtlint: disable=swallowed-exception - best-effort kill; death reconciliation owns the state
                pass
        if no_restart:
            actor.state = "DEAD"
            actor.death_cause = cause
            actor.ready_event.set()
            if actor.name:
                self.named_actors.pop(
                    (actor.spec.get("namespace", "default"), actor.name), None
                )
            self._mark_dirty("actors", actor.actor_id)
            await self.publish("actor_state", actor.snapshot())

    async def rpc_restart_actor(self, conn, payload) -> dict:
        """Resurrect a DEAD actor through the normal lease path — the
        rtdag supervisor's recovery primitive. The replacement may land
        on any node with capacity (the supervisor re-derives channel
        families from the new placement). Idempotent twice over: the
        mutation token absorbs re-sends, and by state — an actor already
        PENDING/RESTARTING/ALIVE is where the caller wants it."""
        cached = self._mutation_cached(payload)
        if cached is not None:
            return cached
        actor = self.actors.get(payload["actor_id"])
        if actor is None:
            return self._mutation_record(payload, {"status": "missing"})
        if actor.state != "DEAD":
            return self._mutation_record(
                payload, {"status": "ok", "state": actor.state}
            )
        actor.state = "RESTARTING"
        actor.death_cause = None
        actor.address = None
        actor.worker_id = None
        actor.ready_event.clear()
        if actor.name:
            # Death evicted the name; the resurrected actor reclaims it
            # unless someone else took it in the meantime.
            self.named_actors.setdefault(
                (actor.spec.get("namespace", "default"), actor.name),
                actor.actor_id,
            )
        self._mark_dirty("actors", actor.actor_id)
        await self.publish("actor_state", actor.snapshot())
        spawn_task(self._schedule_actor(actor))
        return self._mutation_record(
            payload, {"status": "ok", "state": "RESTARTING"}
        )

    async def rpc_list_actors(self, conn, payload) -> list:
        return [a.snapshot() for a in self.actors.values()]

    # ------------------------------------------------------------------
    # placement groups (2-phase commit across agents) [N3]
    # ------------------------------------------------------------------
    async def rpc_create_placement_group(self, conn, payload) -> dict:
        cached = self._mutation_cached(payload)
        if cached is not None:
            return cached
        if payload["pg_id"] in self.pgs:  # idempotent re-send (see create_actor)
            return self._mutation_record(
                payload, {"status": "ok", "pg_id": payload["pg_id"]}
            )
        pg = PlacementGroupInfo(
            payload["pg_id"],
            payload["bundles"],
            payload.get("strategy", "PACK"),
            payload.get("name", ""),
            payload.get("job_id", ""),
        )
        self.pgs[pg.pg_id] = pg
        self._mark_dirty("pgs", pg.pg_id)
        spawn_task(self._schedule_pg(pg))
        return self._mutation_record(
            payload, {"status": "ok", "pg_id": pg.pg_id}
        )

    def _plan_bundles(self, pg: PlacementGroupInfo) -> list[NodeInfo] | None:
        """Pick a node per bundle honoring the strategy. Pure function of the
        current availability snapshot (gcs_placement_group_scheduler.cc)."""
        alive = [n for n in self.nodes.values() if n.alive]
        if not alive:
            return None
        needed = [
            (i, pg.bundles[i])
            for i in range(len(pg.bundles))
            if pg.bundle_nodes[i] is None
        ]
        avail = {n.node_id: dict(n.resources_available) for n in alive}

        def can_host(node_id: str, bundle: dict) -> bool:
            return all(
                avail[node_id].get(k, 0.0) + 1e-9 >= v for k, v in bundle.items() if v > 0
            )

        def consume(node_id: str, bundle: dict) -> None:
            for k, v in bundle.items():
                avail[node_id][k] = avail[node_id].get(k, 0.0) - v

        plan: dict[int, NodeInfo] = {}
        strategy = pg.strategy
        if strategy in ("STRICT_PACK", "PACK"):
            # Try to land everything on one node first.
            for node in sorted(alive, key=self._utilization):
                trial = {n.node_id: dict(n.resources_available) for n in alive}
                ok = True
                for _, bundle in needed:
                    if all(trial[node.node_id].get(k, 0) + 1e-9 >= v for k, v in bundle.items() if v > 0):
                        for k, v in bundle.items():
                            trial[node.node_id][k] = trial[node.node_id].get(k, 0) - v
                    else:
                        ok = False
                        break
                if ok:
                    return [
                        node if pg.bundle_nodes[i] is None else self.nodes[pg.bundle_nodes[i]]
                        for i in range(len(pg.bundles))
                    ]
            if strategy == "STRICT_PACK":
                return None
        if strategy in ("SPREAD", "STRICT_SPREAD"):
            used_nodes: set[str] = {n for n in pg.bundle_nodes if n}
            for index, bundle in needed:
                choice = None
                for node in sorted(alive, key=self._utilization):
                    if strategy == "STRICT_SPREAD" and (
                        node.node_id in used_nodes
                        or any(p.node_id == node.node_id for p in plan.values())
                    ):
                        continue
                    if can_host(node.node_id, bundle):
                        choice = node
                        break
                if choice is None:
                    return None
                plan[index] = choice
                consume(choice.node_id, bundle)
        else:  # PACK fallback / DEFAULT: bin-pack greedily
            for index, bundle in needed:
                choice = None
                for node in sorted(alive, key=lambda n: -self._utilization(n)):
                    if can_host(node.node_id, bundle):
                        choice = node
                        break
                if choice is None:
                    return None
                plan[index] = choice
                consume(choice.node_id, bundle)
        return [
            plan[i] if pg.bundle_nodes[i] is None else self.nodes[pg.bundle_nodes[i]]
            for i in range(len(pg.bundles))
        ]

    async def _schedule_pg(self, pg: PlacementGroupInfo) -> None:
        deadline = time.monotonic() + 120.0
        while pg.state in ("PENDING", "RESCHEDULING"):
            placement = self._plan_bundles(pg)
            if placement is not None:
                # Optimistic reservation at PLAN time (see _debit), before
                # any await: concurrent PG bursts each plan against the
                # post-debit view and spread across nodes. Debiting only
                # after the prepare reply lets every coroutine plan onto
                # the same emptiest node, partially reserve, collide, and
                # roll back in lockstep — a livelock under bursts.
                debited = [
                    (index, placement[index])
                    for index in range(len(pg.bundles))
                    if pg.bundle_nodes[index] is None
                ]
                for index, node in debited:
                    self._debit(node, pg.bundles[index])
                # Phase 1: prepare (reserve) every missing bundle.
                prepared: list[tuple[int, NodeInfo]] = []
                ok = True
                for index, node in debited:
                    try:
                        client = await self._node_client(node)
                        resp = await client.call(
                            "prepare_bundle",
                            {
                                "pg_id": pg.pg_id,
                                "bundle_index": index,
                                "resources": pg.bundles[index],
                            },
                        )
                        if resp["status"] != "ok":
                            ok = False
                            break
                        prepared.append((index, node))
                    except Exception:
                        ok = False
                        break
                if ok:
                    # Phase 2: commit. A node dying between prepare and
                    # commit aborts this round: roll back and retry.
                    committed: list[int] = []
                    try:
                        for index, node in prepared:
                            client = await self._node_client(node)
                            await client.call(
                                "commit_bundle",
                                {"pg_id": pg.pg_id, "bundle_index": index},
                            )
                            pg.bundle_nodes[index] = node.node_id
                            committed.append(index)
                    except Exception:
                        ok = False
                        for index in committed:
                            pg.bundle_nodes[index] = None
                if ok:
                    pg.state = "CREATED"
                    pg.ready_event.set()
                    self._mark_dirty("pgs", pg.pg_id)
                    await self.publish("pg_state", pg.snapshot())
                    # pg-strategy leases may be parked waiting for this.
                    self._notify_capacity()
                    return
                # Rollback: credit every plan-time debit, release the
                # bundles that actually got reserved (committed included).
                for index, node in debited:
                    self._credit(node, pg.bundles[index])
                for index, node in prepared:
                    try:
                        client = await self._node_client(node)
                        await client.call(
                            "release_bundle",
                            {"pg_id": pg.pg_id, "bundle_index": index},
                        )
                    except Exception:  # rtlint: disable=swallowed-exception - rollback of a failed placement; node death frees bundles
                        pass
            if time.monotonic() > deadline:
                await self.publish("pg_state", pg.snapshot())
                return  # stays PENDING (autoscaler hint); creator may time out
            await self._wait_for_capacity(1.0)

    async def rpc_pg_ready(self, conn, payload) -> dict:
        pg = self.pgs.get(payload["pg_id"])
        if pg is None:
            return {"status": "missing"}
        await pg.ready_event.wait()
        return {"status": "ok", "pg": pg.snapshot()}

    async def rpc_remove_placement_group(self, conn, payload) -> dict:
        pg = self.pgs.get(payload["pg_id"])
        if pg is None:
            return {"status": "missing"}
        await self._remove_pg(pg)
        return {"status": "ok"}

    async def _remove_pg(self, pg: PlacementGroupInfo) -> None:
        pg.state = "REMOVED"
        self._mark_dirty("pgs", pg.pg_id)
        for index, node_id in enumerate(pg.bundle_nodes):
            node = self.nodes.get(node_id or "")
            if node is None or not node.alive:
                continue
            try:
                client = await self._node_client(node)
                await client.call(
                    "release_bundle", {"pg_id": pg.pg_id, "bundle_index": index}
                )
            except Exception:  # rtlint: disable=swallowed-exception - node gone: nothing left to release
                pass
        await self.publish("pg_state", pg.snapshot())

    async def rpc_list_placement_groups(self, conn, payload) -> list:
        return [pg.snapshot() for pg in self.pgs.values()]

    # ------------------------------------------------------------------
    # task events / state API feed [N5]
    # ------------------------------------------------------------------
    async def rpc_report_task_events(self, conn, payload) -> dict:
        self.task_events.extend(payload["events"])
        self.events.emit("task_events", payload["events"])
        return {"status": "ok"}

    async def rpc_list_task_events(self, conn, payload) -> list:
        limit = payload.get("limit", 1000)
        events = list(self.task_events)[-limit:]
        return events

    async def rpc_list_tasks(self, conn, payload) -> list:
        """Latest state per task, reduced from the task-event log HERE —
        filters/limit are pushed down so the client never ships 100k raw
        events over the wire just to keep 1000 rows."""
        filters = payload.get("filters") or {}
        limit = payload.get("limit", 1000)
        latest: dict[str, dict] = {}
        for event in self.task_events:
            task_id = event.get("task_id")
            if not task_id:
                continue
            row = latest.setdefault(
                task_id,
                {
                    "task_id": task_id,
                    "name": event.get("name"),
                    "state": None,
                    "node_id": event.get("node_id"),
                    "start_time": None,
                    "end_time": None,
                },
            )
            state = event.get("state")
            row["state"] = state
            if event.get("name"):
                row["name"] = event["name"]
            ts = event.get("ts")
            if state in ("RUNNING",) and ts:
                row["start_time"] = ts
            if event.get("start_ts"):
                # terminal events carry the span start (single-event form)
                row["start_time"] = event["start_ts"]
            if state in ("FINISHED", "FAILED") and ts:
                row["end_time"] = ts
            # Per-task resource attribution (ISSUE 5): terminal events
            # carry the worker's peak RSS / RSS delta (and HBM delta on
            # TPU) across the execution.
            for key in ("peak_rss", "rss_delta", "hbm_delta"):
                if event.get(key) is not None:
                    row[key] = event[key]
        rows = list(latest.values())
        if filters:
            rows = [
                row for row in rows
                if all(row.get(k) == v for k, v in filters.items())
            ]
        return rows[:limit]

    # ------------------------------------------------------------------
    # cluster state queries
    # ------------------------------------------------------------------
    async def rpc_list_nodes(self, conn, payload) -> list:
        return [n.snapshot() for n in self.nodes.values()]

    async def rpc_cluster_resources(self, conn, payload) -> dict:
        total: dict[str, float] = {}
        for node in self.nodes.values():
            if node.alive:
                for k, v in node.resources_total.items():
                    total[k] = total.get(k, 0.0) + v
        return total

    async def rpc_available_resources(self, conn, payload) -> dict:
        total: dict[str, float] = {}
        for node in self.nodes.values():
            if node.alive:
                for k, v in node.resources_available.items():
                    total[k] = total.get(k, 0.0) + v
        return total

    async def rpc_list_workers(self, conn, payload) -> list:
        return list(self.clients.values())

    # ------------------------------------------------------------------
    # resource telemetry (ISSUE 5)
    # ------------------------------------------------------------------
    async def rpc_resource_summary(self, conn, payload) -> dict:
        """Per-node latest sample + ring depths, plus node liveness — the
        payload behind util/state.summarize_resources() and `top`."""
        summary = self.telemetry.summary()
        for node_id, entry in summary["nodes"].items():
            node = self.nodes.get(node_id)
            entry["alive"] = bool(node and node.alive)
        summary["oom_risk_events"] = self.stats_counters.get(
            "oom_risk_events", 0
        )
        return summary

    async def rpc_resource_timeline(self, conn, payload) -> dict:
        return self.telemetry.timeline(
            payload.get("node_id", ""), payload.get("tier")
        )

    # ------------------------------------------------------------------
    # workload flight recorder (ISSUE 8)
    # ------------------------------------------------------------------
    async def rpc_workload_ingest(self, conn, payload) -> dict:
        """Batched flight-recorder samples from a train driver or serve
        proxy: ``{"series": [{"key": ..., "samples": [...]}, ...]}``. The
        store's monotonic guard makes re-delivery (chaos dup/replay, or a
        driver retrying a push) idempotent."""
        ingested = 0
        for entry in payload.get("series", []) or []:
            if not isinstance(entry, dict):
                continue
            samples = entry.get("samples", [])
            if not isinstance(samples, list):
                continue
            ingested += self.telemetry.add_workload_many(
                entry.get("key", ""), samples
            )
        self.stats_counters["workload_ingests"] += 1
        return {"status": "ok", "ingested": ingested}

    async def rpc_workload_summary(self, conn, payload) -> dict:
        return self.telemetry.workload_summary()

    async def rpc_workload_timeline(self, conn, payload) -> dict:
        return self.telemetry.workload_timeline(
            payload.get("key", ""), payload.get("tier")
        )

    async def rpc_report_oom_risk(self, conn, payload) -> dict:
        """Trend-aware OOM early warning from a node agent: count it (the
        metric) and export/publish it (the structured event) so dashboards
        and subscribers see the risk before any kill fires."""
        self.stats_counters["oom_risk_events"] += 1
        await self.publish("oom_risk", payload)
        return {"status": "ok"}

    # ------------------------------------------------------------------
    # comm hang doctor (ISSUE 14)
    # ------------------------------------------------------------------
    async def rpc_report_comm_stall(self, conn, payload) -> dict:
        """A rank's comm watchdog suspects a stall: record it, publish it
        on the event channel, and kick off (debounced) the cluster-wide
        evidence harvest that turns suspicion into a named hang report."""
        self.stats_counters["comm_stall_events"] += 1
        event = dict(payload or {})
        event.setdefault("received_at", time.time())
        self._comm_stalls.append(event)
        await self.publish("comm_stall", event)
        cooldown = float(
            os.environ.get("RAY_TPU_HANG_HARVEST_COOLDOWN_S", "10")
        )
        now = time.monotonic()
        if (
            self._hang_harvest_task is None
            or self._hang_harvest_task.done()
        ) and now - self._last_hang_harvest >= cooldown:
            self._last_hang_harvest = now
            self._hang_harvest_task = spawn_task(
                self._harvest_hang_evidence()
            )
        # A persistent comm stall is also a profiling trigger (ISSUE 20):
        # the hang report names WHO is stuck, the auto-capture names WHAT
        # the stuck rank is doing. Cooldown-guarded inside.
        self._maybe_auto_profile_capture(reason="comm_stall")
        return {"status": "ok"}

    async def _harvest_hang_evidence(self) -> dict:
        """Fan the ``comm_evidence`` RPC across every alive node agent
        and merge the pile into one hang report."""
        from ray_tpu._private import hang_doctor

        alive = [n for n in self.nodes.values() if n.alive]
        evidence: dict[str, dict] = {}
        for node in alive:
            try:
                client = await self._node_client(node)
                evidence[node.node_id] = await client.call(
                    "comm_evidence", {"last_n": 256}, timeout=30.0
                )
            except Exception as exc:  # rtlint: disable=swallowed-exception - a dead/partitioned node IS evidence; the report names what it did reach
                evidence[node.node_id] = {
                    "status": "error", "error": str(exc)
                }
        # build_report's first call walks the package for the static
        # commgraph (file I/O + AST parse) — keep it off the event loop.
        report = await asyncio.to_thread(
            hang_doctor.build_report, list(self._comm_stalls), evidence
        )
        self._hang_reports.append(report)
        self.stats_counters["hang_reports"] += 1
        return report

    async def rpc_hang_report(self, conn, payload) -> dict:
        """Latest merged hang report (``fresh=True`` harvests now — the
        `ray_tpu doctor --hang` path when no stall has auto-fired)."""
        if (payload or {}).get("fresh") or not self._hang_reports:
            report = await self._harvest_hang_evidence()
        else:
            report = self._hang_reports[-1]
        if not (payload or {}).get("stacks", True):
            report = dict(report, stacks={})
        return {"status": "ok", "report": report}

    async def rpc_cluster_stacks(self, conn, payload) -> dict:
        """Native stack dump of every worker on every alive node (the
        `ray_tpu stacks` CLI) — one agent hop per node, no py-spy."""
        alive = [n for n in self.nodes.values() if n.alive]
        out: dict[str, dict] = {}
        for node in alive:
            try:
                client = await self._node_client(node)
                res = await client.call(
                    "comm_evidence", {"last_n": 0, "stacks": True},
                    timeout=30.0,
                )
                out[node.node_id] = res
            except Exception as exc:  # rtlint: disable=swallowed-exception - unreachable node still listed, with the error in its slot
                out[node.node_id] = {"status": "error", "error": str(exc)}
        return {"status": "ok", "nodes": out}

    async def rpc_comm_summary(self, conn, payload) -> dict:
        """Live comm-plane stall view for `ray_tpu top` / the dashboard:
        recent stall events, per-worker in-flight gauges (read straight
        from the metrics KV mirror — snapshots, never drained), and the
        hang-report count."""
        inflight: dict[str, dict] = {}
        for key, raw in self.kv.get("metrics", {}).items():
            if not key.startswith(
                ("rt_comm_inflight{", "rt_comm_inflight_oldest_age_s{")
            ):
                continue
            try:
                point = json.loads(raw)
            except Exception:  # rtlint: disable=swallowed-exception - one corrupt KV point must not hide the rest
                continue
            worker = point.get("tags", {}).get("worker", "?")
            slot = inflight.setdefault(
                worker, {"inflight": 0.0, "oldest_age_s": 0.0, "ts": 0.0}
            )
            if point.get("name") == "rt_comm_inflight":
                slot["inflight"] = point.get("value", 0.0)
            else:
                slot["oldest_age_s"] = point.get("value", 0.0)
            slot["ts"] = max(slot["ts"], point.get("ts", 0.0))
        stalls = list(self._comm_stalls)
        last_stall = stalls[-1] if stalls else None
        return {
            "status": "ok",
            "stall_total": self.stats_counters.get("comm_stall_events", 0),
            "stalls": stalls[-32:],
            "last_stall_age_s": (
                max(0.0, time.time() - last_stall.get("received_at", 0.0))
                if last_stall else None
            ),
            "inflight": inflight,
            "hang_reports": len(self._hang_reports),
        }

    # ------------------------------------------------------------------
    # cluster step profiler (ISSUE 20)
    # ------------------------------------------------------------------
    def _maybe_auto_profile_capture(
        self, reason: str, ranks: list | None = None, steps: int | None = None
    ) -> bool:
        """Debounced auto-capture entry: one capture at a time, one per
        RAY_TPU_PROFILE_AUTO_COOLDOWN_S, nothing when auto is off."""
        from ray_tpu._private import profiler as profiler_mod

        if not profiler_mod.knob_bool("AUTO", True):
            return False
        if self._profile_task is not None and not self._profile_task.done():
            return False
        now = time.monotonic()
        cooldown = profiler_mod.knob_float("AUTO_COOLDOWN_S", 300.0)
        if self._last_auto_profile and now - self._last_auto_profile < cooldown:
            return False
        self._last_auto_profile = now
        capture_id = f"prof-{next(self._profile_seq):04d}-{reason}"
        self._active_capture_id = capture_id
        self._profile_task = spawn_task(
            self._run_profile_capture(
                capture_id,
                steps or profiler_mod.knob_int("AUTO_STEPS", 3),
                ranks,
                reason,
            )
        )
        return True

    async def rpc_profile_capture(self, conn, payload) -> dict:
        """Start one coordinated step-aligned capture (the `ray_tpu
        profile` CLI and the straggler/comm-stall auto-triggers). Returns
        the capture id immediately; poll ``profile_status`` for the
        record (captures span N live train steps — longer than an RPC
        deadline should be)."""
        payload = payload or {}
        reason = str(payload.get("reason") or "manual")
        steps = max(1, int(payload.get("steps") or 3))
        ranks = payload.get("ranks")
        if ranks is not None:
            ranks = [int(r) for r in ranks]
        if reason != "manual":
            started = self._maybe_auto_profile_capture(
                reason=reason, ranks=ranks, steps=steps
            )
            if not started:
                return {"status": "skipped", "code": "cooldown_or_busy"}
            return {
                "status": "ok",
                "capture_id": getattr(self, "_active_capture_id", None),
            }
        if self._profile_task is not None and not self._profile_task.done():
            return {
                "status": "error",
                "code": "busy",
                "error": "a capture is already running",
            }
        capture_id = f"prof-{next(self._profile_seq):04d}-manual"
        self._active_capture_id = capture_id
        self._profile_task = spawn_task(
            self._run_profile_capture(capture_id, steps, ranks, reason)
        )
        return {"status": "ok", "capture_id": capture_id}

    async def rpc_profile_status(self, conn, payload) -> dict:
        """One capture's record (or its in-flight state) by capture id;
        no id → the most recent record."""
        capture_id = (payload or {}).get("capture_id")
        for rec in reversed(self._profiles):
            if capture_id in (None, rec.get("capture_id")):
                return {"status": "ok", "state": "done", "record": rec}
        if self._profile_task is not None and not self._profile_task.done():
            return {"status": "ok", "state": "running", "record": None}
        return {"status": "ok", "state": "unknown", "record": None}

    async def rpc_profile_list(self, conn, payload) -> dict:
        """Completed capture records, oldest first (``ray_tpu diagnose``
        and the dashboard /api/profiles read this)."""
        return {"status": "ok", "profiles": list(self._profiles)}

    async def _profile_fanout(
        self, action: str, targets: dict | None, args: dict | None = None
    ) -> dict:
        """One profiler action across node agents in parallel.
        ``targets``: {node_id: [worker_ids]} to address specific workers,
        None for the all-workers status sweep. Returns {worker_id:
        result} merged across nodes."""
        alive = [n for n in self.nodes.values() if n.alive]
        if targets is not None:
            alive = [n for n in alive if n.node_id in targets]

        async def _one(node):
            try:
                client = await self._node_client(node)
                payload = {"action": action, "args": args or {}}
                if targets is not None:
                    payload["workers"] = list(targets.get(node.node_id) or [])
                return await client.call("profile_gang", payload, timeout=30.0)
            except Exception as exc:  # rtlint: disable=swallowed-exception - an unreachable node yields a partial capture, not a failed one
                return {"status": "error", "error": str(exc)}

        merged: dict[str, dict] = {}
        for node, res in zip(
            alive, await asyncio.gather(*(_one(n) for n in alive))
        ):
            for wid, wres in (res.get("workers") or {}).items():
                if isinstance(wres, dict):
                    wres.setdefault("node_id", node.node_id)
                    merged[wid] = wres
        return merged

    async def _run_profile_capture(
        self,
        capture_id: str,
        steps: int,
        ranks: list | None,
        reason: str,
    ) -> dict:
        """The coordinated capture: discover train ranks + their current
        steps, arm every selected rank at the same upcoming step
        boundary, wait the capture out, collect, merge into ONE Perfetto
        trace + merged folded stacks, record + publish."""
        from ray_tpu._private import profile_merge, profiler as profiler_mod
        from ray_tpu._private.atomic_io import atomic_write_json

        rec: dict = {
            "capture_id": capture_id,
            "ts": time.time(),
            "reason": reason,
            "steps": steps,
            "requested_ranks": ranks,
        }
        try:
            statuses = await self._profile_fanout("status", None)
            train = {
                wid: st
                for wid, st in statuses.items()
                if st.get("status") == "ok" and st.get("rank") is not None
            }
            if ranks is not None:
                train = {
                    wid: st
                    for wid, st in train.items()
                    if int(st["rank"]) in ranks
                }
            if not train:
                rec.update(status="error", code="no_train_workers")
                self._profiles.append(rec)
                await self.publish("profile", rec)
                return rec
            # The SAME upcoming boundary for every rank: past the fastest
            # rank's current step, plus slack for the arm RPC to land.
            known = [
                int(st["step"]) for st in train.values()
                if st.get("step") is not None
            ]
            start_step = (max(known) + 2) if known else 0
            max_s = profiler_mod.knob_float("MAX_S", 60.0)
            targets: dict[str, list[str]] = {}
            for wid, st in train.items():
                targets.setdefault(st.get("node_id") or "", []).append(wid)
            armed = await self._profile_fanout(
                "arm",
                targets,
                {
                    "capture_id": capture_id,
                    "start_step": start_step,
                    "steps": steps,
                    "max_s": max_s,
                    "session_dir": self.session_dir,
                },
            )
            arm_errors = {
                wid: res for wid, res in armed.items()
                if res.get("status") != "ok"
            }
            deadline = time.monotonic() + max_s + 15.0
            pending = set(wid for wid in train if wid not in arm_errors)
            while pending and time.monotonic() < deadline:
                await asyncio.sleep(0.25)
                polled = await self._profile_fanout("status", targets)
                pending = {
                    wid for wid in pending
                    if polled.get(wid, {}).get("state")
                    in ("armed", "capturing")
                }
            if pending:
                # Deadline elapsed with ranks still armed/capturing (step
                # stream stalled?): force-stop them so collect returns a
                # (partial) capture instead of `not_done`.
                stuck = {
                    nid: [w for w in wids if w in pending]
                    for nid, wids in targets.items()
                    if any(w in pending for w in wids)
                }
                await self._profile_fanout("abort", stuck)
            collected = await self._profile_fanout("collect", targets)
            captures = [
                res for res in collected.values()
                if res.get("status") == "ok"
            ]
            out_dir = os.path.join(self.session_dir, "profiles", capture_id)
            trace = profile_merge.merge_captures(
                captures,
                capture_id,
                meta={"reason": reason, "start_step": start_step},
            )
            folded = profile_merge.merge_folded(captures)
            trace_path = os.path.join(out_dir, "merged_trace.json")
            folded_path = os.path.join(out_dir, "merged_folded.json")
            await asyncio.to_thread(atomic_write_json, trace_path, trace)
            await asyncio.to_thread(atomic_write_json, folded_path, folded)
            hot = {}
            for cap in captures:
                if cap.get("rank") is None:
                    continue
                phase, frac = profile_merge.hot_phase(
                    cap.get("phase_totals") or {}
                )
                if phase is not None:
                    hot[str(cap["rank"])] = {
                        "phase": phase, "frac": round(frac, 4)
                    }
            rec.update(
                status="ok" if captures and not arm_errors else "partial",
                ranks=trace["metadata"]["ranks"],
                start_step=start_step,
                path=trace_path,
                folded_path=folded_path,
                hot_phases=hot,
                workers=len(captures),
                arm_errors={
                    wid: res.get("code") or res.get("error")
                    for wid, res in arm_errors.items()
                } or None,
                trace_ids=trace["metadata"]["trace_ids"],
            )
            if not captures:
                rec["status"] = "error"
                rec["code"] = "no_captures"
        except Exception as exc:
            print(
                f"[controller] profile capture {capture_id} failed: {exc}",
                file=sys.stderr, flush=True,
            )
            rec.update(status="error", code="exception", error=str(exc))
        self._profiles.append(rec)
        self.stats_counters["profile_captures"] += 1
        await self.publish("profile", rec)
        return rec

    async def rpc_controller_stats(self, conn, payload) -> dict:
        """Control-plane internals for the scale suite and /metrics: queue
        depths must drain to zero in a healthy cluster."""
        states = collections.Counter(a.state for a in self.actors.values())
        pg_states = collections.Counter(p.state for p in self.pgs.values())
        return {
            "counters": dict(self.stats_counters),
            "pending_lease_shapes": len(self._pending_leases),
            "pending_lease_depth": sum(
                len(q) for q in self._pending_leases.values()
            ),
            "pending_demands": len(self.pending_demands),
            "pub_outbox_depth": sum(
                len(v) for v in self._pub_outbox.values()
            ),
            "subscriber_conns": len(
                {c for s in self.subscribers.values() for c in s}
            ),
            "snapshot": dict(self._snap_stats),
            "snapshot_store": self.store.stats(),
            "mutation_cache_size": len(self._mutation_replies),
            "nodes_alive": sum(1 for n in self.nodes.values() if n.alive),
            "actor_states": dict(states),
            "pg_states": dict(pg_states),
            "node_stats": {
                n.node_id: n.stats for n in self.nodes.values() if n.stats
            },
            "telemetry": self.telemetry.stats(),
        }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--session-dir", required=True)
    args = parser.parse_args()

    async def run() -> None:
        controller = Controller(args.session_dir)
        port = await controller.start(args.host, args.port)
        # Write the bound port for the parent to discover. Atomic: the
        # parent polls for this file and must never read a torn half.
        from ray_tpu._private.atomic_io import atomic_write_json

        atomic_write_json(
            os.path.join(args.session_dir, "controller.addr"),
            {"host": args.host, "port": port},
        )
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()

"""Standalone external KV store speaking wire v1.

The deployment-side half of controller HA (reference:
redis_store_client.cc's Redis, SURVEY N7): a tiny durable KV service the
controller can point its snapshot store at
(RAY_TPU_controller_store=kv://host:port). Keys persist to an
append-compact JSON file, so the service itself survives restarts.

    python -m ray_tpu._private.kv_store_server --port 6399 \
        --data /var/lib/raytpu-kv.json
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import os
import sys

from ray_tpu._private import atomic_io
from ray_tpu._private.rpc import RpcServer


class KVStoreServer:
    def __init__(self, data_path: str | None = None):
        self.data_path = data_path
        self.kv: dict[str, dict[str, bytes]] = {}
        self._load()

    def _load(self) -> None:
        if not self.data_path or not os.path.exists(self.data_path):
            return
        try:
            with open(self.data_path) as fh:
                raw = json.load(fh)
            self.kv = {
                ns: {
                    key: base64.b64decode(value)
                    for key, value in entries.items()
                }
                for ns, entries in raw.items()
            }
        except Exception as exc:
            print(f"[raytpu-kv] load failed: {exc}", file=sys.stderr)

    def _flush(self) -> None:
        if not self.data_path:
            return
        raw = {
            ns: {
                key: base64.b64encode(value).decode()
                for key, value in entries.items()
            }
            for ns, entries in self.kv.items()
        }
        atomic_io.atomic_write_json(self.data_path, raw)

    async def rpc_kv_put(self, conn, payload) -> dict:
        ns = payload.get("namespace", "default")
        key = payload["key"]
        entries = self.kv.setdefault(ns, {})
        if key in entries and not payload.get("overwrite", True):
            return {"status": "exists"}
        entries[key] = payload["value"]
        self._flush()
        return {"status": "ok"}

    async def rpc_kv_get(self, conn, payload) -> dict:
        ns = payload.get("namespace", "default")
        value = self.kv.get(ns, {}).get(payload["key"])
        if value is None:
            return {"status": "missing"}
        return {"status": "ok", "value": value}

    async def rpc_kv_del(self, conn, payload) -> dict:
        ns = payload.get("namespace", "default")
        existed = self.kv.get(ns, {}).pop(payload["key"], None) is not None
        self._flush()
        return {"status": "ok", "deleted": existed}

    async def rpc_kv_keys(self, conn, payload) -> dict:
        ns = payload.get("namespace", "default")
        return {"status": "ok", "keys": sorted(self.kv.get(ns, {}))}

    async def rpc_ping(self, conn, payload) -> dict:
        return {"status": "ok", "role": "raytpu-kv-store"}


async def run(host: str, port: int, data_path: str | None,
              ready_file: str | None = None) -> None:
    store = KVStoreServer(data_path)
    server = RpcServer(name="kv-store")
    server.route_object(store)
    bound = await server.start(host, port)
    print(f"[raytpu-kv] listening on {host}:{bound}", flush=True)
    if ready_file:
        # Atomic: the parent polls for this file to learn the bound port.
        atomic_io.atomic_write_json(
            ready_file, {"host": host, "port": bound}
        )
    while True:
        await asyncio.sleep(3600)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--data", default=None)
    parser.add_argument("--ready-file", default=None)
    args = parser.parse_args()
    asyncio.run(run(args.host, args.port, args.data, args.ready_file))


if __name__ == "__main__":
    main()

"""Per-node agent — worker pool, local scheduling, object plane host.

Role-equivalent of the reference raylet
(src/ray/raylet/main.cc + node_manager.cc :: NodeManager [N9]) including:
  * WorkerPool            — worker_pool.cc [N11]: spawn/cache/kill workers,
                            per-runtime-env pools, registration handshake
  * lease queue           — local_task_manager.cc-style grant queue [N10]
  * bundle reservations   — placement-group prepare/commit/release (the
                            raylet side of the GCS 2PC [N3])
  * object plane host     — owns the shared-memory store server [N17] and
                            serves chunked pulls (object_manager.cc [N16])
  * resource reporting    — heartbeats to the controller (ray_syncer [N33])
  * worker-death watch    — SIGCHLD-equivalent monitoring, reports to the
                            controller for actor restart decisions
  * log forwarding        — log_monitor.py-equivalent: worker stdout/stderr
                            to per-session files + pubsub to drivers
  * TPU detection         — enumerates local TPU chips into the node's
                            resource vocabulary (the TPU-native addition)
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import itertools
import json
import os
import signal
import sys
import time
from typing import Any

from ray_tpu._private import chaos
from ray_tpu._private.config import global_config
from ray_tpu._private.ids import WorkerID
from ray_tpu._private.object_store import ObjectStoreClient, ObjectStoreServer
from ray_tpu._private.rpc import RpcClient, RpcServer, ServerConnection, spawn_task
from ray_tpu._private.runtime_env import RuntimeEnvManager
from ray_tpu.util import tracing


def detect_tpu_resources() -> dict:
    """TPU topology detection (SURVEY §2.1 'TPU build implication').

    Order: (1) RAY_TPU_tpu_slice_override flag (resource lying for tests,
    §4.4.3), (2) /dev/accel* | /dev/vfio device nodes (TPU VM), (3) opt-in
    jax probe in a throwaway subprocess (RAY_TPU_DETECT_TPU=1) — never in
    this process: initializing the TPU backend here would hold the chip lock
    the workers need, and costs ~20s of agent startup.
    """
    override = global_config().tpu_slice_override
    if override:
        # e.g. "v4-8" -> 4 chips (v4/v5p sizes count TensorCores)
        try:
            generation, size = override.split("-")
            chips = max(1, int(size) // 2) if generation in ("v4", "v5p") else int(size)
            return {"TPU": float(chips), f"TPU-{override}": float(chips)}
        except ValueError:
            return {}
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return {}
    try:
        accels = [d for d in os.listdir("/dev") if d.startswith("accel")]
        if accels:
            return {"TPU": float(len(accels))}
    except OSError:
        pass
    if os.environ.get("RAY_TPU_DETECT_TPU") == "1":  # pragma: no cover
        import subprocess as sp

        try:
            out = sp.run(
                [sys.executable, "-c",
                 "import jax,json;print(json.dumps([d.device_kind for d in "
                 "jax.devices() if d.platform=='tpu']))"],
                capture_output=True, text=True, timeout=60,
            )
            kinds = json.loads(out.stdout.strip().splitlines()[-1])
            if kinds:
                kind = kinds[0].replace(" ", "-")
                return {"TPU": float(len(kinds)), f"TPU-{kind}": float(len(kinds))}
        except Exception:  # rtlint: disable=swallowed-exception - TPU probe: any failure means no TPUs to advertise
            pass
    return {}


def _gc_stale_arenas() -> None:
    """Unlink arena files left by SIGKILLed agents (their Stop() never
    ran). A stale arena pins real tmpfs memory, and on this class of host
    growing resident shm measurably slows page supply for everyone.
    Filename layout: /dev/shm/raytpu-<agent_pid>-<node_suffix>."""
    try:
        for name in os.listdir("/dev/shm"):
            if not name.startswith("raytpu-"):
                continue
            parts = name.split("-")
            if len(parts) < 3 or not parts[1].isdigit():
                continue
            pid = int(parts[1])
            try:
                os.kill(pid, 0)  # alive? leave it
            except ProcessLookupError:
                try:
                    os.unlink(os.path.join("/dev/shm", name))
                except OSError:
                    pass
            except PermissionError:
                pass
    except OSError:
        pass


class WorkerProcess:
    def __init__(
        self,
        worker_id: str,
        proc: asyncio.subprocess.Process,
        env_hash: str,
        job_id: str = "",
    ):
        self.worker_id = worker_id
        self.proc = proc
        self.env_hash = env_hash
        self.job_id = job_id
        self.address: tuple | None = None
        self.registered = asyncio.Event()
        self.actor_id: str | None = None
        self.intended_exit = False
        self.resources: dict = {}
        self.bundle: dict | None = None
        # Set by the memory monitor before the SIGKILL so _watch_worker
        # can attribute the death ("oom") instead of a generic crash.
        self.death_reason: str | None = None
        self.oom_rss: int | None = None


class Lease:
    _ids = itertools.count(1)

    def __init__(
        self, worker: WorkerProcess, resources: dict, bundle_key: tuple | None
    ):
        self.lease_id = f"lease-{next(Lease._ids)}"
        self.worker = worker
        self.resources = resources
        # Resolved (pg_id, bundle_index) the resources were consumed from —
        # never the caller's raw request (whose index may be the -1 wildcard).
        self.bundle_key = bundle_key


class NodeAgent:
    def __init__(
        self,
        node_id: str,
        controller_addr: tuple,
        session_dir: str,
        resources: dict | None = None,
        store_capacity: int = 0,
        labels: dict | None = None,
    ):
        self.node_id = node_id
        self.controller_addr = controller_addr
        self.session_dir = session_dir
        self.labels = labels or {}
        self.server = RpcServer(name=f"agent-{node_id[:10]}")
        self.controller: RpcClient | None = None
        self.address: tuple | None = None

        if store_capacity <= 0:
            import psutil

            store_capacity = min(
                int(psutil.virtual_memory().total * 0.3), 16 * (1 << 30)
            )
        self.store_capacity = store_capacity
        suffix = node_id[-8:]
        self.store_socket = os.path.join(session_dir, f"store-{suffix}.sock")
        self.store_shm = f"/dev/shm/raytpu-{os.getpid()}-{suffix}"
        _gc_stale_arenas()
        self.spill_dir = os.path.join(session_dir, f"spill-{suffix}")
        self.store_server: ObjectStoreServer | None = None
        self._store_client: ObjectStoreClient | None = None

        base = {"CPU": float(os.cpu_count() or 1), "memory": float(store_capacity)}
        base.update(detect_tpu_resources())
        base[f"node:{node_id}"] = 1.0
        if resources:
            base.update({k: float(v) for k, v in resources.items()})
        self.resources_total = base
        self.resources_available = dict(base)

        self.workers: dict[str, WorkerProcess] = {}
        self.idle_workers: dict[str, list[WorkerProcess]] = {}
        # Tombstones for owners asking WHY a worker died (OOM vs crash);
        # bounded so long-lived agents don't accumulate forever.
        self.death_info: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        self.runtime_envs = RuntimeEnvManager(session_dir)
        self.leases: dict[str, Lease] = {}
        self.bundles: dict[tuple, dict] = {}  # (pg_id, idx) -> {resources, available, committed}
        # Parked lease requests indexed by resource shape (sorted names):
        # a freed resource wakes only the shapes it can satisfy instead of
        # thundering every waiter on every release. Key () = any shape.
        self._resource_waiters: dict[tuple, list[asyncio.Future]] = {}
        self.log_dir = os.path.join(session_dir, "logs")
        tracing.configure(session_dir)
        os.makedirs(self.log_dir, exist_ok=True)
        os.makedirs(self.spill_dir, exist_ok=True)
        # object-transfer plane (N16): agent→agent push clients + counters
        # (counters surface in store_stats so tests can assert "no pull")
        self._transfer_clients: dict[tuple, RpcClient] = {}
        self.pull_chunks_served = 0
        self.pushes_started = 0
        self.pushes_received = 0
        # native lease lane (N9/N10): engine handle when enabled; the C++
        # table is then the single source of truth for non-bundle node
        # resources; _native_leases mirrors grants via drained events.
        self._native_lease = None
        self._native_leases: dict[str, dict] = {}
        self._default_env_hash = self._env_hash({})
        # resource telemetry (ISSUE 5): the memory-monitor loop assembles
        # node samples here; the heartbeat loop ships them piggybacked on
        # the existing stats channel. Bounded: a controller outage drops
        # old samples instead of growing the agent.
        self._telemetry_buffer: collections.deque = collections.deque(maxlen=64)
        self._telemetry_last_sample = 0.0
        # per-worker (t, rss) history for the oom_risk trend projection
        self._rss_history: dict[str, collections.deque] = {}
        self._oom_risk_last: dict[str, float] = {}

    # ------------------------------------------------------------------
    async def start(self, port: int = 0) -> tuple:
        self.store_server = ObjectStoreServer(
            self.store_socket, self.store_shm, self.store_capacity, self.spill_dir
        )
        self.server.route_object(self)
        if hasattr(self.server, "route_push"):
            # C++ object-transfer plane: the engine reassembles obj_chunk
            # frames and posts ONE obj_complete per object (N16 push path).
            self.server.route_push("obj_complete", self._on_obj_complete)
            # native lease lane: resources freed in C++ wake Python's
            # blocked lease requests immediately
            self.server.route_push("lease_freed", self._on_lease_freed)
        bound = await self.server.start("127.0.0.1", port)
        if global_config().native_lease_lane:
            # Native lease lane (local_task_manager.cc grant role): the
            # engine grants simple leases on its own thread; Python keeps
            # the policy/slow paths and adjusts the same native counters.
            try:
                from ray_tpu._private.rpc import _NativeEngine

                engine = _NativeEngine.for_running_loop()
                self._native_lease = engine
                self._lease_adjust_native(self.resources_available, +1)
                engine.lib.rt_lease_enable(engine.handle, 1)
            except Exception:
                self._native_lease = None
        self.address = ("127.0.0.1", bound)
        chaos.set_identity(f"node:{self.node_id}")
        self.controller = RpcClient(
            self.controller_addr, name="agent-to-controller", auto_reconnect=True
        )
        self.controller.chaos_peer = "controller"
        await self.controller.connect()
        # Survive controller restarts: replay registration on reconnect
        # (reference: raylet re-registers through gcs_client reconnect).
        self.controller.on_reconnect = self._register_with_controller
        await self._register_with_controller()
        spawn_task(self._heartbeat_loop())
        spawn_task(self._memory_monitor_loop())
        return self.address

    async def _memory_monitor_loop(self) -> None:
        """Per-worker RSS watchdog (reference: memory_monitor.cc + the
        raylet OOM-kill policy, N15). When node usage crosses
        memory_usage_threshold, the largest-RSS worker is killed; any
        worker above memory_worker_rss_limit_mb (absolute cap, also the
        testing knob) is killed outright. The owner of its tasks sees a
        retriable OutOfMemoryError (via worker_death_info), never a
        whole-node OOM.

        The same psutil sweep doubles as the node's resource-telemetry
        sampler (ISSUE 5): at most once per telemetry_sample_interval_s it
        assembles a node sample (CPU%, per-worker RSS, object-store bytes,
        HBM when available) into _telemetry_buffer for the heartbeat to
        ship, and feeds the per-worker RSS histories behind the
        trend-aware ``oom_risk`` early warning."""
        import psutil

        cfg = global_config()
        interval = cfg.memory_monitor_interval_s
        if interval <= 0:
            return
        self._last_pressure_kill = 0.0
        procs: dict[str, "psutil.Process"] = {}
        while True:
            await asyncio.sleep(interval)
            limit_bytes = cfg.memory_worker_rss_limit_mb * (1 << 20)
            try:
                vmem = psutil.virtual_memory()
                node_frac = vmem.percent / 100.0
            except Exception:  # rtlint: disable=swallowed-exception - psutil sampling hiccup; retry next interval
                continue
            over_node = node_frac >= cfg.memory_usage_threshold
            now = time.time()
            want_sample = (
                cfg.telemetry_enabled
                and now - self._telemetry_last_sample
                >= cfg.telemetry_sample_interval_s
            )
            want_risk = limit_bytes > 0 and cfg.oom_risk_horizon_s > 0
            if not over_node and limit_bytes <= 0 and not want_sample:
                continue
            samples = []
            live_ids = set()
            for worker in list(self.workers.values()):
                pid = getattr(worker.proc, "pid", None)
                if pid is None or worker.proc.returncode is not None:
                    continue
                live_ids.add(worker.worker_id)
                try:
                    proc = procs.get(worker.worker_id)
                    # Stale-handle guard: a respawned worker id carries a
                    # new pid, and a reused pid is a different process
                    # (is_running() compares create_time) — either way the
                    # cached handle would read a stranger's RSS.
                    if proc is not None and (
                        proc.pid != pid or not proc.is_running()
                    ):
                        procs.pop(worker.worker_id, None)
                        proc = None
                    if proc is None:
                        proc = procs[worker.worker_id] = psutil.Process(pid)
                    samples.append((proc.memory_info().rss, worker))
                except psutil.NoSuchProcess:
                    procs.pop(worker.worker_id, None)
                    continue
                except Exception:  # rtlint: disable=swallowed-exception - per-proc sampling race; skip this worker this tick
                    continue
            for worker_id in list(procs):
                if worker_id not in live_ids:
                    procs.pop(worker_id, None)
            if want_sample:
                self._telemetry_last_sample = now
                self._telemetry_sample(now, vmem, samples)
            if want_risk:
                self._check_oom_risk(now, samples, limit_bytes, cfg)
            if not over_node and limit_bytes <= 0:
                continue
            if not samples:
                continue
            # Kill preference (raylet policy analog): retriable task
            # workers before actors, largest RSS first.
            samples.sort(key=lambda item: (item[1].actor_id is not None,
                                           -item[0]))
            to_kill = []
            if limit_bytes > 0:
                to_kill = [s for s in samples if s[0] > limit_bytes]
            # Node-pressure kills need a grace period: freeing tens of GB
            # takes longer than one tick, and an unreaped victim still
            # counts in virtual_memory() — without the gate one spike
            # cascade-kills healthy workers (raylet waits for a kill to
            # take effect before choosing another victim).
            kill_pending = any(
                w.death_reason is not None for w in self.workers.values()
            )
            in_grace = (
                time.monotonic() - self._last_pressure_kill
                < max(1.0, 4 * interval)
            )
            if over_node and not to_kill and not kill_pending and not in_grace:
                to_kill = [samples[0]]  # preferred offender
                self._last_pressure_kill = time.monotonic()
            for rss, worker in to_kill:
                if worker.death_reason is not None:
                    continue
                worker.death_reason = "oom"
                worker.oom_rss = rss
                if self._native_lease is not None:
                    # never pool a dying worker: the engine's return path
                    # must bounce this worker's lease back to Python
                    self._native_lease.lib.rt_lease_worker_ban(
                        self._native_lease.handle, worker.worker_id.encode()
                    )
                print(
                    f"[raytpu-agent] memory monitor killing worker "
                    f"{worker.worker_id} (rss={rss >> 20} MiB, "
                    f"node={node_frac:.0%})",
                    file=sys.stderr,
                )
                self._kill_worker_tree(worker)

    @staticmethod
    def _kill_worker_tree(worker: WorkerProcess) -> None:
        """SIGKILL the worker AND any subprocesses the task spawned.
        Workers deliberately share the agent's session (node teardown
        kills the whole group), so a group kill is not available —
        psutil's recursive child walk reaches forked helpers instead."""
        try:
            import psutil

            for child in psutil.Process(worker.proc.pid).children(
                recursive=True
            ):
                try:
                    child.kill()
                except Exception:  # rtlint: disable=swallowed-exception - child already exited
                    pass
        except Exception:  # rtlint: disable=swallowed-exception - process tree gone mid-walk
            pass
        try:
            worker.proc.kill()
        except ProcessLookupError:
            pass

    # ------------------------------------------------------------------
    # resource telemetry (ISSUE 5)
    # ------------------------------------------------------------------
    def _telemetry_sample(self, now: float, vmem, samples: list) -> None:
        """Assemble one node sample from the monitor sweep and buffer it
        for the next heartbeat (piggyback channel — no extra RPC)."""
        import psutil

        worker_rss = {w.worker_id: int(rss) for rss, w in samples}
        sample: dict[str, Any] = {
            "ts": now,
            "mem_used": int(vmem.total - vmem.available),
            "mem_total": int(vmem.total),
            "num_workers": len(self.workers),
            "workers_rss_total": sum(worker_rss.values()),
            "workers_rss_max": max(worker_rss.values(), default=0),
            "worker_rss": worker_rss,
        }
        try:
            # Non-blocking since-last-call percent; the first call of a
            # process returns 0.0 and primes the counter.
            sample["cpu_percent"] = psutil.cpu_percent(None)
        except Exception:  # rtlint: disable=swallowed-exception - cpu sampling is advisory telemetry
            pass
        try:
            store_stats = self.store.stats()
            sample["object_store_bytes"] = int(store_stats.get("used", 0))
            sample["object_store_capacity"] = int(
                store_stats.get("capacity", 0)
            )
        except Exception:  # rtlint: disable=swallowed-exception - store stats are advisory telemetry
            pass
        sample.update(self._hbm_stats())
        self._telemetry_buffer.append(sample)

    def _hbm_stats(self) -> dict:
        """TPU HBM used/total via jax.local_devices() memory_stats() —
        only when jax is ALREADY imported in this process. The agent never
        imports jax itself: initializing the TPU backend here would steal
        the chip lock from workers (see detect_tpu_resources)."""
        mod = sys.modules.get("jax")
        if mod is None:
            return {}
        try:
            used = total = 0
            for dev in mod.local_devices():
                if getattr(dev, "platform", "") != "tpu":
                    continue
                mem = dev.memory_stats() or {}
                used += int(mem.get("bytes_in_use", 0))
                total += int(mem.get("bytes_limit", 0))
            if total:
                return {"hbm_used": used, "hbm_total": total}
        except Exception:  # rtlint: disable=swallowed-exception - hbm stats are advisory telemetry
            pass
        return {}

    def _check_oom_risk(
        self, now: float, samples: list, limit_bytes: int, cfg
    ) -> None:
        """Trend-aware early warning: when a worker's RSS slope projects
        past the kill limit within oom_risk_horizon_s while its current
        RSS is still under it, report ``oom_risk`` to the controller
        (structured event + metric) BEFORE the point-in-time kill fires."""
        from ray_tpu._private.telemetry import project_rss

        live = set()
        for rss, worker in samples:
            wid = worker.worker_id
            live.add(wid)
            hist = self._rss_history.get(wid)
            if hist is None:
                hist = self._rss_history[wid] = collections.deque(maxlen=8)
            hist.append((now, rss))
            if rss >= limit_bytes:
                continue  # the kill path owns this case
            projected = project_rss(hist, cfg.oom_risk_horizon_s)
            if projected is None or projected < limit_bytes:
                continue
            if now - self._oom_risk_last.get(wid, 0.0) < cfg.oom_risk_cooldown_s:
                continue
            self._oom_risk_last[wid] = now
            print(
                f"[raytpu-agent] oom_risk: worker {wid} rss={rss >> 20} MiB "
                f"projected={int(projected) >> 20} MiB crosses limit "
                f"{limit_bytes >> 20} MiB within {cfg.oom_risk_horizon_s:.0f}s",
                file=sys.stderr,
            )
            spawn_task(
                self._report_oom_risk(
                    {
                        "node_id": self.node_id,
                        "worker_id": wid,
                        "actor_id": worker.actor_id,
                        "rss": int(rss),
                        "projected_rss": int(projected),
                        "limit_bytes": int(limit_bytes),
                        "horizon_s": cfg.oom_risk_horizon_s,
                        "ts": now,
                    }
                )
            )
        for wid in list(self._rss_history):
            if wid not in live:
                self._rss_history.pop(wid, None)
                self._oom_risk_last.pop(wid, None)

    async def _report_oom_risk(self, payload: dict) -> None:
        try:
            await self.controller.call("report_oom_risk", payload)
        except Exception:  # rtlint: disable=swallowed-exception - advisory: never let a warning RPC hurt the agent
            pass  # advisory: never let a warning RPC hurt the agent

    async def _register_with_controller(self) -> None:
        resp = await self.controller.call(
            "register_node",
            {
                "node_id": self.node_id,
                "agent_addr": list(self.address),
                "resources": self.resources_total,
                "labels": self.labels,
                "store_info": self.store_info(),
                # For post-restart reconciliation: actors this node still
                # hosts (a restored ALIVE actor missing here is dead; one
                # the snapshot caught pre-ALIVE is re-attached from this).
                "live_actors": [
                    {
                        "actor_id": w.actor_id,
                        "worker_id": w.worker_id,
                        "addr": list(w.address) if w.address else None,
                    }
                    for w in self.workers.values()
                    if w.actor_id
                ],
                # 2PC reservations held here — lets a restarted controller
                # release prepares its dead predecessor never committed.
                "held_bundles": [
                    {"pg_id": key[0], "index": key[1]}
                    for key in self.bundles
                ],
            },
        )
        # Ghost-worker cleanup after a partition heal: the controller
        # failed these actors over (or they relocated) while we were cut
        # off — keeping their old incarnations alive here would answer
        # stale handles alongside the replacement.
        for entry in (resp or {}).get("stale_actors") or []:
            worker = self.workers.get(entry.get("worker_id") or "")
            if worker is None or worker.actor_id != entry.get("actor_id"):
                continue
            print(
                f"[raytpu-agent] killing ghost worker {worker.worker_id} "
                f"(actor {worker.actor_id} superseded during partition)",
                file=sys.stderr,
            )
            worker.intended_exit = True
            self._kill_worker_tree(worker)

    def store_info(self) -> dict:
        return {
            "socket": self.store_socket,
            "shm_path": self.store_shm,
            "capacity": self.store_capacity,
        }

    @property
    def store(self) -> ObjectStoreClient:
        if self._store_client is None:
            self._store_client = ObjectStoreClient(
                self.store_socket, self.store_shm, self.store_capacity
            )
        return self._store_client

    def _loop_engine(self):
        """The running loop's native RPC engine, or None (asyncio backend)."""
        try:
            from ray_tpu._private.rpc import _NativeEngine

            loop = asyncio.get_event_loop()
            with _NativeEngine._lock:
                return _NativeEngine._by_loop.get(id(loop))
        except Exception:  # rtlint: disable=swallowed-exception - native engine optional; asyncio backend has none
            return None

    def _agent_stats(self) -> dict:
        """Cheap local counters piggybacked on each heartbeat so the
        controller aggregates cluster health without extra RPC fan-out."""
        stats = {
            "workers": len(self.workers),
            "idle_workers": sum(len(v) for v in self.idle_workers.values()),
            "leases": len(self.leases) + len(self._native_leases),
            "bundles": len(self.bundles),
            "resource_waiters": sum(
                len(v) for v in self._resource_waiters.values()
            ),
        }
        engine = self._loop_engine()
        if engine is not None and hasattr(engine, "stats"):
            try:
                stats["engine"] = engine.stats()
            except Exception:  # rtlint: disable=swallowed-exception - engine stats are advisory telemetry
                pass
        return stats

    async def _heartbeat_loop(self) -> None:
        cfg = global_config()
        while True:
            await asyncio.sleep(cfg.health_check_period_ms / 1000.0)
            try:
                self._refresh_available_mirror()
                self._drain_lease_events()
                payload = {
                    "node_id": self.node_id,
                    "resources_available": self.resources_available,
                    "stats": self._agent_stats(),
                }
                # Telemetry piggyback: snapshot (don't drain) the buffer so
                # a failed send retries the same samples next beat — the
                # controller's monotonic-ts guard dedups any replay.
                shipped = list(self._telemetry_buffer)
                if shipped:
                    payload["telemetry"] = shipped
                resp = await self.controller.call("heartbeat", payload)
                for _ in shipped:  # delivered: drop exactly what we sent
                    try:
                        self._telemetry_buffer.popleft()
                    except IndexError:
                        break
                if resp.get("status") in ("unknown_node", "reregister"):
                    # unknown_node: controller restarted without a snapshot
                    # of us. reregister: the controller declared us dead
                    # (partition outlasted the health timeout) and refuses
                    # to silently resurrect — a full re-registration
                    # reconciles live actors/bundles and has the reply name
                    # any ghost workers we must kill.
                    await self._register_with_controller()
            except Exception:
                # Controller unreachable: auto_reconnect redials on the
                # next call; brief pause avoids a hot loop.
                await asyncio.sleep(1.0)

    # ------------------------------------------------------------------
    # resource accounting (native lease table when enabled — one source
    # of truth shared with the engine's grant path)
    # ------------------------------------------------------------------
    def _lease_adjust_native(
        self, resources: dict, sign: int, check: bool = False
    ) -> bool:
        import ctypes

        engine = self._native_lease
        items = [(k, float(v)) for k, v in resources.items() if v > 0]
        if not items:
            return True
        names = b"".join(k.encode() + b"\0" for k, _ in items)
        deltas = (ctypes.c_double * len(items))(
            *[sign * v for _, v in items]
        )
        return bool(
            engine.lib.rt_lease_adjust(
                engine.handle, names, deltas, len(items), 1 if check else 0
            )
        )

    def _refresh_available_mirror(self) -> None:
        """Pull the native table into self.resources_available (reporting
        paths only; accounting always goes through the native adjust)."""
        engine = self._native_lease
        if engine is None:
            return
        import ctypes

        buf = ctypes.create_string_buffer(16384)
        n = engine.lib.rt_lease_available_json(engine.handle, buf, 16384)
        if n > 0:
            try:
                native = json.loads(buf.value.decode())
            except ValueError:
                return
            merged = dict(self.resources_available)
            merged.update(native)
            self.resources_available = merged

    def _drain_lease_events(self) -> None:
        """Reconcile native grants/returns into _native_leases (needed by
        the bounced return path and worker-death cleanup)."""
        engine = self._native_lease
        if engine is None:
            return
        import ctypes

        buf = ctypes.create_string_buffer(8192)
        while True:
            n = engine.lib.rt_lease_next_event(engine.handle, buf, 8192)
            if n <= 0:
                return
            try:
                event = json.loads(buf.value.decode())
            except ValueError:
                continue
            if event.get("ev") == "grant":
                self._native_leases[event["lease_id"]] = event
            else:
                self._native_leases.pop(event.get("lease_id"), None)

    def _try_consume(self, resources: dict, bundle_key: tuple | None) -> bool:
        if bundle_key is None and self._native_lease is not None:
            return self._lease_adjust_native(resources, -1, check=True)
        pool = (
            self.bundles[bundle_key]["available"]
            if bundle_key is not None and bundle_key in self.bundles
            else self.resources_available
        )
        for k, v in resources.items():
            if v > 0 and pool.get(k, 0.0) + 1e-9 < v:
                return False
        for k, v in resources.items():
            if v > 0:
                pool[k] = pool.get(k, 0.0) - v
        return True

    def _wake_waiters(self, freed: dict | None = None) -> None:
        """Wake parked lease requests whose resource shape overlaps the
        freed keys (all shapes when *freed* is None/unknown)."""
        if not self._resource_waiters:
            return
        if freed is None:
            shapes = list(self._resource_waiters)
        else:
            freed_keys = {k for k, v in freed.items() if v > 0}
            shapes = [
                s for s in self._resource_waiters
                if not s or not freed_keys.isdisjoint(s)
            ]
        for shape in shapes:
            for waiter in self._resource_waiters.pop(shape, ()):
                if not waiter.done():
                    waiter.set_result(None)

    def _give_back(self, resources: dict, bundle_key: tuple | None) -> None:
        if bundle_key is None and self._native_lease is not None:
            self._lease_adjust_native(resources, +1)
            self._wake_waiters(resources)
            return
        if bundle_key is not None:
            bundle = self.bundles.get(bundle_key)
            # Bundle already released (PG teardown raced this worker/lease
            # death): release_bundle returned the bundle's FULL allocation
            # to the node pool, so crediting the node again here would
            # double-count — two later bundles could then commit onto one
            # slot (observed as a 4-worker gang on 3 one-slot nodes).
            pool = None if bundle is None else bundle["available"]
        else:
            pool = self.resources_available
        if pool is not None:
            for k, v in resources.items():
                if v > 0:
                    pool[k] = pool.get(k, 0.0) + v
        self._wake_waiters(resources)

    async def _on_lease_freed(self, conn, raw) -> None:
        """The engine returned a native lease: its freed resources must
        wake any Python-path request parked in _wait_for_resources."""
        freed = None
        if isinstance(raw, dict):
            freed = raw.get("resources") or None
        self._wake_waiters(freed)

    async def _wait_for_resources(self, resources: dict | None = None) -> None:
        shape = tuple(sorted(k for k, v in (resources or {}).items() if v > 0))
        future = asyncio.get_running_loop().create_future()
        self._resource_waiters.setdefault(shape, []).append(future)
        try:
            await asyncio.wait_for(future, timeout=5.0)
        except asyncio.TimeoutError:
            pass
        finally:
            bucket = self._resource_waiters.get(shape)
            if bucket is not None:
                if future in bucket:
                    bucket.remove(future)
                if not bucket:
                    self._resource_waiters.pop(shape, None)

    # ------------------------------------------------------------------
    # worker pool [N11]
    # ------------------------------------------------------------------
    def _env_hash(self, runtime_env: dict) -> str:
        return repr(sorted((runtime_env or {}).items()))

    def _pop_idle_worker(self, env_hash: str, job_id: str):
        """Reuse a live idle worker only when it belongs to the SAME job —
        its log-forwarding tasks and RAYTPU_JOB_ID were bound at spawn, so
        a cross-job handout would misroute stdout/err to the old driver."""
        if (
            self._native_lease is not None
            and env_hash == self._default_env_hash
        ):
            # default-env idle workers live in the NATIVE pool (shared
            # with the engine's grant path — one pool, no double-grant)
            import ctypes

            engine = self._native_lease
            buf = ctypes.create_string_buffer(128)
            while engine.lib.rt_lease_pool_pop(
                engine.handle, job_id.encode(), buf, 128
            ):
                worker = self.workers.get(buf.value.decode())
                if (
                    worker is not None
                    and worker.proc.returncode is None
                    and worker.death_reason is None
                ):
                    return worker
            return None
        pool = self.idle_workers.get(env_hash) or []
        for i in range(len(pool) - 1, -1, -1):
            candidate = pool[i]
            if (
                candidate.proc.returncode is not None
                or candidate.death_reason is not None
            ):
                pool.pop(i)
                continue
            if candidate.job_id == job_id:
                pool.pop(i)
                return candidate
        return None

    async def _spawn_worker(
        self, runtime_env: dict, job_id: str, actor_mode: bool = False
    ) -> WorkerProcess:
        worker_id = WorkerID.random()
        env = dict(os.environ)
        # Materialize pip/py_modules/working_dir through the runtime-env
        # manager (URI cache + per-job refcount, reference runtime_env
        # agent role) before the worker exists.
        env_ctx = await self.runtime_envs.setup(runtime_env, job_id)
        env.update(env_ctx.env_vars)
        if env_ctx.python_paths:
            existing_pp = env.get("PYTHONPATH", "")
            env["PYTHONPATH"] = os.pathsep.join(
                env_ctx.python_paths + ([existing_pp] if existing_pp else [])
            )
        env.update(
            {
                "RAYTPU_WORKER_ID": worker_id,
                "RAYTPU_NODE_ID": self.node_id,
                "RAYTPU_JOB_ID": job_id,
                "RAYTPU_CONTROLLER": json.dumps(list(self.controller_addr)),
                "RAYTPU_AGENT": json.dumps(list(self.address)),
                "RAYTPU_STORE": json.dumps(self.store_info()),
                "RAYTPU_SESSION_DIR": self.session_dir,
            }
        )
        proc = await asyncio.create_subprocess_exec(
            sys.executable,
            "-u",
            "-m",
            "ray_tpu._private.worker_proc",
            env=env,
            cwd=env_ctx.working_dir or None,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        worker = WorkerProcess(
            worker_id, proc, self._env_hash(runtime_env), job_id
        )
        self.workers[worker_id] = worker
        loop = asyncio.get_running_loop()
        spawn_task(self._forward_logs(worker, proc.stdout, "out", job_id))
        spawn_task(self._forward_logs(worker, proc.stderr, "err", job_id))
        spawn_task(self._watch_worker(worker))
        try:
            await asyncio.wait_for(
                worker.registered.wait(),
                timeout=global_config().worker_register_timeout_s,
            )
        except asyncio.TimeoutError:
            try:
                proc.kill()
            except ProcessLookupError:
                pass
            self.workers.pop(worker_id, None)
            raise RuntimeError("worker failed to register in time")
        return worker

    async def _forward_logs(self, worker, stream, kind: str, job_id: str) -> None:
        path = os.path.join(
            self.log_dir, f"worker-{worker.worker_id[-12:]}.{kind}"
        )
        # rtlint: disable=blocking-in-async - unbuffered append of single lines to a local log; a thread hop per line would cost more than the write
        with open(path, "ab", buffering=0) as sink:
            while True:
                try:
                    line = await stream.readline()
                except (ValueError, asyncio.LimitOverrunError):
                    continue
                if not line:
                    break
                sink.write(line)
                try:
                    await self.controller.call(
                        "publish",
                        {
                            "channel": "logs",
                            "message": {
                                "job_id": job_id,
                                "pid": worker.proc.pid,
                                "kind": kind,
                                "line": line.decode(errors="replace").rstrip("\n"),
                            },
                        },
                    )
                except Exception:  # rtlint: disable=swallowed-exception - log forwarding is best-effort during controller restart
                    pass

    async def _watch_worker(self, worker: WorkerProcess) -> None:
        code = await worker.proc.wait()
        self.workers.pop(worker.worker_id, None)
        engine = self._native_lease
        if engine is not None:
            # purge from the engine's idle pool and release any native
            # lease the dead worker still held; the ban (if any) can go —
            # this worker_id will never be pooled again
            engine.lib.rt_lease_pool_remove(
                engine.handle, worker.worker_id.encode()
            )
            engine.lib.rt_lease_worker_unban(
                engine.handle, worker.worker_id.encode()
            )
            self._drain_lease_events()
            for lease_id, event in list(self._native_leases.items()):
                if event.get("worker_id") == worker.worker_id:
                    self._native_leases.pop(lease_id, None)
                    engine.lib.rt_lease_forget(
                        engine.handle, lease_id.encode()
                    )
                    self._give_back(event.get("resources", {}), None)
        self.death_info[worker.worker_id] = {
            "reason": worker.death_reason
            or ("intended" if worker.intended_exit else "crash"),
            "exit_code": code,
            "rss": worker.oom_rss,
        }
        while len(self.death_info) > 256:
            self.death_info.popitem(last=False)
        pool = self.idle_workers.get(worker.env_hash)
        if pool and worker in pool:
            pool.remove(worker)
        if worker.job_id and not any(
            w.job_id == worker.job_id for w in self.workers.values()
        ):
            # Last worker of the job on this node: drop its runtime-env
            # references so unreferenced envs become GC-eligible.
            self.runtime_envs.release_job(worker.job_id)
        # Release any lease resources still held.
        for lease in [l for l in self.leases.values() if l.worker is worker]:
            self.leases.pop(lease.lease_id, None)
            self._give_back(lease.resources, lease.bundle_key)
        if worker.actor_id and worker.resources:
            self._give_back(
                worker.resources,
                (worker.bundle["pg_id"], worker.bundle["bundle_index"])
                if worker.bundle
                else None,
            )
        try:
            await self.controller.call(
                "worker_died",
                {
                    "worker_id": worker.worker_id,
                    "node_id": self.node_id,
                    "actor_id": worker.actor_id,
                    "exit_code": code,
                    "intended": worker.intended_exit,
                    "reason": worker.death_reason,
                },
            )
        except Exception as exc:
            # The controller missing a death report delays actor restart
            # until its own liveness probe fires — worth a breadcrumb.
            print(
                f"[raytpu-agent] worker_died report for "
                f"{worker.worker_id} failed: {exc!r}",
                file=sys.stderr, flush=True,
            )

    async def rpc_worker_death_info(self, conn, payload) -> dict:
        """Why a worker died (owner-side OOM attribution, N15). `alive`
        lets callers stop polling: a live worker will never grow a
        tombstone."""
        worker_id = payload.get("worker_id", "")
        worker = self.workers.get(worker_id)
        # "alive" must be false while a kill is in flight (death mark set,
        # process not yet reaped) — the tombstone IS coming; callers that
        # stopped polling here would misattribute an OOM as a crash.
        alive = (
            worker is not None
            and worker.proc.returncode is None
            and worker.death_reason is None
        )
        return {
            "status": "ok",
            "info": self.death_info.get(worker_id),
            "alive": alive,
        }

    # ------------------------------------------------------------------
    # RPC: worker registration + leases
    # ------------------------------------------------------------------
    async def rpc_register_worker(self, conn: ServerConnection, payload) -> dict:
        worker = self.workers.get(payload["worker_id"])
        if worker is None:
            return {"status": "unknown_worker"}
        worker.address = tuple(payload["address"])
        worker.registered.set()
        return {"status": "ok"}

    async def rpc_lease_worker(self, conn, payload) -> dict:
        resources = payload["resources"]
        runtime_env = payload.get("runtime_env") or {}
        bundle = payload.get("bundle")
        bundle_key = (bundle["pg_id"], bundle["bundle_index"]) if bundle else None
        if bundle_key is not None and bundle_key not in self.bundles:
            # bundle_index -1: any bundle of the pg on this node
            if bundle and bundle["bundle_index"] == -1:
                match = next(
                    (k for k in self.bundles if k[0] == bundle["pg_id"]), None
                )
                bundle_key = match
            if bundle_key is None or bundle_key not in self.bundles:
                return {"status": "no_bundle"}
        deadline = time.monotonic() + 8.0
        while not self._try_consume(resources, bundle_key):
            if time.monotonic() > deadline:
                return {"status": "busy"}
            await self._wait_for_resources(resources)
        env_hash = self._env_hash(runtime_env)
        worker = self._pop_idle_worker(env_hash, payload.get("job_id", ""))
        if worker is None:
            trace_ctx = (
                payload.get("trace_ctx") if tracing.enabled() else None
            )
            spawn_start_ns = time.time_ns() if trace_ctx else 0
            try:
                worker = await self._spawn_worker(runtime_env, payload.get("job_id", ""))
            except Exception as exc:
                if trace_ctx:
                    tracing.emit(
                        "worker_start", trace_ctx, start_ns=spawn_start_ns,
                        status="error", node_id=self.node_id,
                        error_type=type(exc).__name__,
                    )
                self._give_back(resources, bundle_key)
                return {"status": "spawn_failed", "error": str(exc)}
            if trace_ctx:
                # Cold-start cost: only emitted when a lease actually
                # forced a spawn (idle-pool hits are free).
                tracing.emit(
                    "worker_start", trace_ctx, start_ns=spawn_start_ns,
                    node_id=self.node_id, worker_id=worker.worker_id,
                )
        lease = Lease(worker, resources, bundle_key)
        self.leases[lease.lease_id] = lease
        return {
            "status": "ok",
            "lease_id": lease.lease_id,
            "worker_id": worker.worker_id,
            "worker_addr": list(worker.address),
        }

    async def rpc_return_worker(self, conn, payload) -> dict:
        lease = self.leases.pop(payload["lease_id"], None)
        if lease is None:
            # Possibly a NATIVE lease bounced here (reusable=False kill
            # path, or a lease granted by the engine for a worker that
            # died): reconcile from the engine's event log.
            self._drain_lease_events()
            native = self._native_leases.pop(payload["lease_id"], None)
            if native is None:
                return {"status": "unknown_lease"}
            engine = self._native_lease
            if engine is not None:
                engine.lib.rt_lease_forget(
                    engine.handle, payload["lease_id"].encode()
                )
            self._give_back(native.get("resources", {}), None)
            worker = self.workers.get(native.get("worker_id", ""))
            if worker is not None and worker.proc.returncode is None:
                # reusable leases never bounce — this is the kill path
                worker.intended_exit = True
                self._kill_worker_tree(worker)
            return {"status": "ok"}
        self._give_back(lease.resources, lease.bundle_key)
        worker = lease.worker
        if worker.proc.returncode is None and not worker.actor_id:
            if payload.get("reusable", True) and worker.death_reason is None:
                if (
                    self._native_lease is not None
                    and worker.env_hash == self._default_env_hash
                    and worker.address is not None
                ):
                    # hand the warm worker to the engine's grant pool —
                    # the next same-job lease never touches asyncio
                    engine = self._native_lease
                    engine.lib.rt_lease_pool_put(
                        engine.handle, worker.worker_id.encode(),
                        worker.job_id.encode(),
                        worker.address[0].encode(),
                        int(worker.address[1]),
                    )
                    return {"status": "ok"}
                self.idle_workers.setdefault(
                    worker.env_hash, []
                ).append(worker)
            else:
                # reusable=False (the owner saw the conn die) or a pending
                # death mark: pooling would burn the next lease's tasks,
                # and leaving the process idling would leak it (and its
                # RSS) forever — kill it; the pool respawns on demand.
                worker.intended_exit = True
                self._kill_worker_tree(worker)
        return {"status": "ok"}

    # ------------------------------------------------------------------
    # RPC: actors
    # ------------------------------------------------------------------
    async def rpc_start_actor(self, conn, payload) -> dict:
        spec = payload["spec"]
        # Idempotent by actor_id: a retried start_actor (dropped reply,
        # duplicated request, controller re-schedule racing a slow ack)
        # must return the EXISTING incarnation, not spawn a second worker
        # that double-consumes resources and runs __init__ twice.
        for worker in self.workers.values():
            if (
                worker.actor_id == spec["actor_id"]
                and worker.proc.returncode is None
                and worker.address is not None
            ):
                return {
                    "status": "ok",
                    "worker_id": worker.worker_id,
                    "worker_addr": list(worker.address),
                    "pid": worker.proc.pid,
                }
        resources = spec.get("resources") or {"CPU": 1}
        strategy = spec.get("scheduling_strategy") or {}
        bundle = None
        bundle_key = None
        if strategy.get("kind") == "pg":
            index = strategy.get("bundle_index", -1)
            if index == -1:
                bundle_key = next(
                    (k for k in self.bundles if k[0] == strategy["pg_id"]), None
                )
            else:
                bundle_key = (strategy["pg_id"], index)
            if bundle_key is None or bundle_key not in self.bundles:
                return {"status": "no_bundle"}
            bundle = {"pg_id": bundle_key[0], "bundle_index": bundle_key[1]}
        if not self._try_consume(resources, bundle_key):
            return {"status": "busy"}
        # Prefer a warm idle worker (reference WorkerPool reuse): a fresh
        # interpreter costs seconds of imports, which under CPU contention
        # can push actor readiness past client deadlines.
        env_hash = self._env_hash(spec.get("runtime_env") or {})
        worker = self._pop_idle_worker(env_hash, spec.get("job_id", ""))
        if worker is None:
            try:
                worker = await self._spawn_worker(
                    spec.get("runtime_env") or {}, spec.get("job_id", ""),
                    actor_mode=True,
                )
            except Exception as exc:
                self._give_back(resources, bundle_key)
                return {"status": "spawn_failed", "error": str(exc)}
        worker.actor_id = spec["actor_id"]
        worker.resources = resources
        worker.bundle = bundle
        worker_client = RpcClient(worker.address, name="agent-to-worker")
        try:
            await worker_client.connect()
            # Bounded: a wedged worker must surface as creation_failed (the
            # controller retries on a fresh worker), not hang the scheduler.
            resp = await worker_client.call(
                "create_actor",
                {"spec": spec, "creation_args": payload.get("creation_args")},
                timeout=global_config().worker_register_timeout_s + 60,
            )
        except Exception as exc:
            self._fail_actor_worker(worker)
            self._give_back(resources, bundle_key)
            return {"status": "creation_failed", "error": str(exc)}
        finally:
            await worker_client.close()
        if resp.get("status") != "ok":
            self._fail_actor_worker(worker)
            self._give_back(resources, bundle_key)
            return {"status": "creation_failed", "error": resp.get("error")}
        return {
            "status": "ok",
            "worker_id": worker.worker_id,
            "worker_addr": list(worker.address),
            "pid": worker.proc.pid,
        }

    def _fail_actor_worker(self, worker: WorkerProcess) -> None:
        """Kill a worker whose actor creation failed. Clears the actor
        bookkeeping FIRST so _watch_worker does not give the same resources
        back a second time (the creation path already does)."""
        worker.actor_id = None
        worker.resources = {}
        worker.bundle = None
        worker.intended_exit = True
        try:
            worker.proc.kill()
        except ProcessLookupError:
            pass

    async def rpc_kill_worker(self, conn, payload) -> dict:
        worker = self.workers.get(payload["worker_id"])
        if worker is None:
            return {"status": "missing"}
        worker.intended_exit = bool(payload.get("intended", True))
        try:
            worker.proc.kill()
        except ProcessLookupError:
            pass
        return {"status": "ok"}

    async def rpc_chaos_kill_worker(self, conn, payload) -> dict:
        """ChaosMonkey hook: SIGKILL one hosted worker, UNintended — the
        death flows through the normal crash-report path (worker_died →
        controller restart policy). Deterministic victim selection:
        workers sorted by worker_id, indexed by the schedule."""
        candidates = sorted(
            (w for w in self.workers.values() if w.proc.returncode is None),
            key=lambda w: w.worker_id,
        )
        if payload.get("prefer") == "actor":
            actor_workers = [w for w in candidates if w.actor_id]
            candidates = actor_workers or candidates
        if not candidates:
            return {"status": "no_workers"}
        worker = candidates[int(payload.get("index", 0)) % len(candidates)]
        worker.death_reason = "chaos"
        self._kill_worker_tree(worker)
        return {
            "status": "ok",
            "worker_id": worker.worker_id,
            "actor_id": worker.actor_id,
        }

    # ------------------------------------------------------------------
    # RPC: placement group bundles (raylet side of the 2PC [N3])
    # ------------------------------------------------------------------
    async def rpc_prepare_bundle(self, conn, payload) -> dict:
        key = (payload["pg_id"], payload["bundle_index"])
        if key in self.bundles:
            return {"status": "ok"}
        resources = payload["resources"]
        if not self._try_consume(resources, None):
            return {"status": "insufficient"}
        self.bundles[key] = {
            "resources": dict(resources),
            "available": dict(resources),
            "committed": False,
        }
        return {"status": "ok"}

    async def rpc_commit_bundle(self, conn, payload) -> dict:
        key = (payload["pg_id"], payload["bundle_index"])
        bundle = self.bundles.get(key)
        if bundle is None:
            return {"status": "missing"}
        bundle["committed"] = True
        return {"status": "ok"}

    async def rpc_release_bundle(self, conn, payload) -> dict:
        key = (payload["pg_id"], payload["bundle_index"])
        bundle = self.bundles.pop(key, None)
        if bundle is None:
            return {"status": "missing"}
        self._give_back(bundle["resources"], None)
        return {"status": "ok"}

    # ------------------------------------------------------------------
    # RPC: object plane (object_manager.cc [N16]: C++ push + chunked pull
    # fallback)
    # ------------------------------------------------------------------
    async def rpc_pull_object_chunk(self, conn, payload) -> dict:
        object_id = payload["object_id"]
        view = self.store.get(object_id, timeout_ms=0)
        if view is None:
            return {"status": "missing"}
        try:
            self.pull_chunks_served += 1
            total = len(view)
            start = payload.get("offset", 0)
            end = min(start + payload.get("chunk", 5 * 1024 * 1024), total)
            return {"status": "ok", "data": bytes(view[start:end]), "total": total}
        finally:
            self.store.release(object_id)

    async def rpc_push_object(self, conn, payload) -> dict:
        """Push one of this node's objects to another node's agent
        (push_manager.cc role): the C++ sender thread slices it into
        obj_chunk frames — no per-chunk Python on either side. Replies
        as soon as the transfer is queued; the pull path remains the
        fallback if the transfer is dropped (budget/conn loss)."""
        import ctypes

        import numpy as np

        from ray_tpu._private.rpc import _NativeEngine

        object_id = payload["object_id"]
        target = (payload["target_host"], payload["target_port"])
        try:
            engine = _NativeEngine.for_running_loop()
        except Exception:
            return {"status": "unsupported"}
        view = self.store.get(object_id, timeout_ms=0)
        if view is None:
            return {"status": "missing"}
        try:
            client = self._transfer_clients.get(target)
            if client is None or not client.connected:
                client = RpcClient(
                    target, name=f"xfer-to-{target[1]}"
                )
                await client.connect()
                self._transfer_clients[target] = client
            conn_id = getattr(client, "_conn_id", None)
            if conn_id is None:
                return {"status": "unsupported"}
            buf = np.frombuffer(view, dtype=np.uint8)
            # Executor thread: rt_push_object memcpys the whole object
            # into the sender's job buffer — a multi-hundred-MB copy must
            # not stall this event loop (engine.lib is CDLL: GIL released)
            loop = asyncio.get_running_loop()
            rc = await loop.run_in_executor(
                None,
                engine.lib.rt_push_object,
                engine.handle, conn_id, object_id.encode(),
                ctypes.c_void_p(buf.ctypes.data), len(view),
            )
            if rc != 0:
                return {"status": "busy" if rc == -1 else "error"}
            self.pushes_started += 1
            return {"status": "ok", "size": len(view)}
        finally:
            self.store.release(object_id)

    async def _on_obj_complete(self, conn, raw) -> None:
        """One inbound object fully reassembled by the engine: land it in
        this node's store and release the C++ buffer."""
        import ctypes

        from ray_tpu._private.rpc import _NativeEngine

        object_id = bytes(raw).decode()
        try:
            engine = _NativeEngine.for_running_loop()
            ptr = ctypes.c_void_p()
            length = ctypes.c_uint64()
            if engine.lib.rt_transfer_take(
                engine.handle, object_id.encode(),
                ctypes.byref(ptr), ctypes.byref(length),
            ) != 0:
                return
            try:
                data = (
                    ctypes.c_ubyte * length.value
                ).from_address(ptr.value)
                try:
                    # .cast("B"): ctypes views carry an endian-prefixed
                    # format that memoryview slice-assign rejects
                    self.store.put(object_id, memoryview(data).cast("B"))
                except FileExistsError:
                    pass
                self.pushes_received += 1
            finally:
                engine.lib.rt_transfer_free(
                    engine.handle, object_id.encode()
                )
        except Exception:  # rtlint: disable=swallowed-exception - pull fallback still serves the object
            pass  # pull fallback still serves the object

    async def rpc_delete_object(self, conn, payload) -> dict:
        ok = self.store.delete(payload["object_id"])
        return {"status": "ok" if ok else "missing"}

    async def rpc_store_stats(self, conn, payload) -> dict:
        stats = self.store.stats()
        stats["transfer"] = {
            "pull_chunks_served": self.pull_chunks_served,
            "pushes_started": self.pushes_started,
            "pushes_received": self.pushes_received,
        }
        # Leases the PYTHON path still holds (direct-lane workers not yet
        # past their reuse grace): lets callers detect true quiescence
        # instead of "at least one worker returned".
        self._drain_lease_events()
        stats["leases_outstanding"] = len(self.leases) + len(self._native_leases)
        engine = self._native_lease
        if engine is not None:
            import ctypes

            out = (ctypes.c_longlong * 4)()
            engine.lib.rt_lease_stats(engine.handle, out)
            stats["native_lease"] = {
                "grants": int(out[0]),
                "returns": int(out[1]),
                "idle_workers": int(out[2]),
                "active": int(out[3]),
            }
        loop_engine = self._loop_engine()
        if loop_engine is not None and hasattr(loop_engine, "stats"):
            try:
                stats["engine"] = loop_engine.stats()
            except Exception:  # rtlint: disable=swallowed-exception - engine stats are advisory telemetry
                pass
        return stats

    async def rpc_runtime_env_info(self, conn, payload) -> dict:
        return self.runtime_envs.cache_info()

    async def _forward_to_worker(
        self, worker_id: str, method: str, payload: dict
    ) -> dict:
        """One-shot RPC into a worker this node hosts (reporter-agent role:
        the dashboard reaches workers through their node agent)."""
        worker = self.workers.get(worker_id or "")
        if worker is None or worker.address is None:
            return {"status": "error", "error": "unknown worker"}
        client = RpcClient(tuple(worker.address), name=f"{method}-fwd")
        try:
            await client.connect(retry=False)
            return await client.call(method, payload, timeout=30.0)
        except Exception as exc:
            return {"status": "error", "error": str(exc)}
        finally:
            await client.close()

    async def rpc_profile_worker(self, conn, payload) -> dict:
        """XLA profiler start/stop on one of this node's workers
        (SURVEY §5.1 TPU-equiv of py-spy/profiler triggers)."""
        return await self._forward_to_worker(
            payload.get("worker_id", ""),
            "profiler",
            {
                "action": payload.get("action"),
                "log_dir": payload.get("log_dir"),
            },
        )

    async def rpc_profile_gang(self, conn, payload) -> dict:
        """Step-profiler fan-out (ISSUE 20, the comm_evidence shape):
        apply one profiler action — arm / status / collect / abort — to
        this node's workers in parallel. ``workers`` limits the fan-out
        to named worker ids (the controller targets the armed ranks);
        absent, every local worker is asked (the status sweep that
        discovers which workers ARE train ranks)."""
        req = dict((payload or {}).get("args") or {})
        req["action"] = (payload or {}).get("action")
        worker_ids = (payload or {}).get("workers")
        if worker_ids is None:
            worker_ids = list(self.workers)
        else:
            worker_ids = [w for w in worker_ids if w in self.workers]
        results = await asyncio.gather(
            *(
                self._forward_to_worker(wid, "profiler", req)
                for wid in worker_ids
            ),
            return_exceptions=True,
        )
        workers = {}
        for wid, res in zip(worker_ids, results):
            if isinstance(res, BaseException):
                res = {"status": "error", "error": str(res)}
            workers[wid] = res
        return {"status": "ok", "node_id": self.node_id, "workers": workers}

    async def rpc_stack_trace_worker(self, conn, payload) -> dict:
        """Live thread stacks of a worker (dashboard 'Stack Trace' role)."""
        return await self._forward_to_worker(
            payload.get("worker_id", ""), "stack_trace", {}
        )

    async def rpc_comm_evidence(self, conn, payload) -> dict:
        """Hang-doctor fan-out: gather every local worker's comm flight
        snapshot (+ stacks) in parallel, one agent hop per node."""
        req = {
            "last_n": int((payload or {}).get("last_n", 256)),
            "stacks": bool((payload or {}).get("stacks", True)),
        }
        worker_ids = list(self.workers)
        results = await asyncio.gather(
            *(
                self._forward_to_worker(wid, "comm_flight", req)
                for wid in worker_ids
            ),
            return_exceptions=True,
        )
        workers = {}
        for wid, res in zip(worker_ids, results):
            if isinstance(res, BaseException):
                res = {"status": "error", "error": str(res)}
            workers[wid] = res
        return {"status": "ok", "node_id": self.node_id, "workers": workers}

    async def rpc_node_info(self, conn, payload) -> dict:
        self._refresh_available_mirror()
        return {
            "node_id": self.node_id,
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "num_workers": len(self.workers),
        }

    async def shutdown(self) -> None:
        for worker in list(self.workers.values()):
            worker.intended_exit = True
            try:
                worker.proc.kill()
            except ProcessLookupError:
                pass
        await self.server.stop()
        if self.store_server is not None:
            self.store_server.stop()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--controller", required=True, help="host:port")
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--store-capacity", type=int, default=0)
    parser.add_argument("--port", type=int, default=0)
    args = parser.parse_args()
    host, port = args.controller.rsplit(":", 1)

    async def run() -> None:
        agent = NodeAgent(
            args.node_id,
            (host, int(port)),
            args.session_dir,
            resources=json.loads(args.resources),
            store_capacity=args.store_capacity,
        )
        addr = await agent.start(args.port)
        # Atomic: the head polls for this discovery file.
        from ray_tpu._private.atomic_io import atomic_write_json

        atomic_write_json(
            os.path.join(args.session_dir, f"agent-{args.node_id[-8:]}.addr"),
            {"addr": list(addr), "store": agent.store_info()},
        )
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()

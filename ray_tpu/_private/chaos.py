"""Deterministic chaos-injection core (transport-level fault plane).

Role-equivalent of the reference's ``RAY_testing_asio_delay_us`` knob grown
into a real fault-injection subsystem (the direction of Jepsen/chaos-mesh
style network fault tooling, scoped to this runtime's wire-v1 transport):
a seeded :class:`FaultSchedule` describes which faults to inject —

  * drop / delay / duplicate / reorder individual RPC messages,
  * asymmetric node-pair partitions on a shared timeline,
  * per-process slowdowns,
  * named fail-points inside subsystems (e.g. the controller's snapshot
    write), and
  * scheduled SIGKILLs (executed by ``ray_tpu.util.chaos.ChaosMonkey``,
    which drives a ``cluster_utils.Cluster``).

Every per-message decision is a **pure function** of
``(seed, decision point, method, per-point counter)`` via SHA-256 — no
shared RNG stream — so two runs issuing the same logical sequence of
RPCs take the identical fault sequence, and every decision that fires is
appended to a per-process JSONL event log for post-hoc assertion.

This module lives in ``_private`` so the transport (``_private/rpc.py``)
can import it without cycles; the public face is ``ray_tpu.util.chaos``.

Config sources, in precedence order:
  1. programmatic :func:`install` (also exports to the environment so
     cluster subprocesses inherit the schedule),
  2. ``RAY_TPU_chaos`` env var — a JSON object or ``@/path/to/file``,
  3. legacy ``RAY_TPU_testing_rpc_delay_ms`` — honored as an alias for a
     delay-only schedule (deprecated; use ``{"delay_ms": N}``).
"""

from __future__ import annotations

import asyncio
import fnmatch
import hashlib
import json
import os
import threading
import time
from typing import Any

from ray_tpu._private.config import global_config

_ENV_SCHEDULE = "RAY_TPU_chaos"
_ENV_IDENTITY = "RAY_TPU_chaos_identity"
_ENV_LOG_DIR = "RAY_TPU_chaos_log_dir"

# Data-plane methods excluded from message-level faults by default: their
# delivery contracts (at-most-once actor calls, streaming object chunks)
# have their own recovery machinery and schedules opt in explicitly.
DEFAULT_EXCLUDE = (
    "push_task",
    "push_actor_task",
    "stream_next",
    "stream_cancel",
    "pull_object_chunk",
    "push_object",
    "obj_chunk",
    "register_worker",
)

# Methods the chaos-aware retry loop must never re-send on timeout: a
# retry would violate at-most-once semantics (these are excluded from
# faults by default anyway, but a user schedule may include them).
NON_RETRYABLE = ("push_actor_task", "push_task")


class ChaosFault(Exception):
    """Raised by an armed fail-point (see FaultSchedule.fail_points)."""


class FaultSchedule:
    """Declarative, seed-reproducible fault schedule.

    Message-fault probabilities are per-RPC and evaluated independently at
    each decision point; ``partitions`` / ``slow`` entries live on a shared
    timeline anchored at ``epoch`` (unix time, set once by whoever creates
    the schedule and inherited by every cluster process).
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        drop_request: float = 0.0,
        drop_reply: float = 0.0,
        dup_request: float = 0.0,
        dup_reply: float = 0.0,
        delay_ms: float = 0.0,
        delay_jitter_ms: float = 0.0,
        reorder: float = 0.0,
        reorder_ms: float = 50.0,
        methods: list[str] | None = None,
        exclude_methods: list[str] | None = None,
        partitions: list[dict] | None = None,
        slow: list[dict] | None = None,
        fail_points: dict[str, int] | None = None,
        latency_points: dict[str, float] | None = None,
        kills: list[dict] | None = None,
        call_timeout_s: float = 2.0,
        max_call_attempts: int = 6,
        epoch: float | None = None,
    ):
        self.seed = int(seed)
        self.drop_request = float(drop_request)
        self.drop_reply = float(drop_reply)
        self.dup_request = float(dup_request)
        self.dup_reply = float(dup_reply)
        self.delay_ms = float(delay_ms)
        self.delay_jitter_ms = float(delay_jitter_ms)
        self.reorder = float(reorder)
        self.reorder_ms = float(reorder_ms)
        self.methods = list(methods) if methods else []
        self.exclude_methods = (
            list(exclude_methods)
            if exclude_methods is not None
            else list(DEFAULT_EXCLUDE)
        )
        # [{"src": "node:*", "dst": "controller", "start_s": 2, "duration_s": 10}]
        self.partitions = list(partitions or [])
        # [{"match": "node:abc*", "extra_ms": 50}]
        self.slow = list(slow or [])
        # {"controller.snapshot_save": 2} -> first 2 hits raise ChaosFault.
        # A value may also be {"count": N, "start_s": X, "duration_s": Y}:
        # armed only inside the epoch-relative window (count -1 = every hit
        # in the window). Windows bound process-kill fail points — a
        # replacement process gets a fresh per-process budget, so an
        # unwindowed kill point would fell every successor too.
        self.fail_points = dict(fail_points or {})
        # {"serve.replica.request": 500.0} -> callers of latency_delay()
        # at that point sleep the given extra milliseconds (slow-replica /
        # tail-latency injection, ISSUE 13). Always-on while armed, unlike
        # fail_points there is no hit budget — slowness is a condition,
        # not an event. A value may also be the windowed dict form
        # {"extra_ms": X, "start_s": S, "duration_s": D} (epoch-relative,
        # like fail_points): the hang-doctor chaos gate uses it to wedge
        # exactly one rank's allreduce for a bounded window.
        self.latency_points = dict(latency_points or {})
        # [{"at_s": 3, "target": "controller"|"agent:<idx>"|"worker:<idx>",
        #   "restart_after_s": 2.0}] — executed by ChaosMonkey, not here.
        self.kills = list(kills or [])
        self.call_timeout_s = float(call_timeout_s)
        self.max_call_attempts = int(max_call_attempts)
        self.epoch = float(epoch) if epoch is not None else time.time()

    # -- (de)serialization ------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({k: v for k, v in vars(self).items()})

    @classmethod
    def from_json(cls, raw: str) -> "FaultSchedule":
        data = json.loads(raw)
        seed = data.pop("seed", 0)
        known = {
            k: v for k, v in data.items()
            if k in cls(0).__dict__  # ignore unknown keys (fwd compat)
        }
        return cls(seed, **known)

    def message_faults_enabled(self) -> bool:
        return any(
            p > 0
            for p in (
                self.drop_request, self.drop_reply, self.dup_request,
                self.dup_reply, self.reorder,
            )
        ) or self.delay_ms > 0 or self.delay_jitter_ms > 0

    def lossy(self) -> bool:
        """True when messages can vanish outright (drops or partitions) —
        only then do calls need the chaos timeout cap + retry loop; a
        delay/dup-only schedule keeps the caller's own timeout semantics."""
        return (
            self.drop_request > 0
            or self.drop_reply > 0
            or bool(self.partitions)
        )

    def targets(self, method: str) -> bool:
        if self.methods:
            return any(fnmatch.fnmatch(method, m) for m in self.methods)
        return not any(
            fnmatch.fnmatch(method, m) for m in self.exclude_methods
        )


class ChaosInjector:
    """Per-process fault decision engine + event log.

    Decisions are derived per decision point from
    ``sha256(seed | point | method | n)`` where ``n`` counts prior
    decisions at that (point, method) in this process — deterministic
    given the same logical call sequence, independent across points.
    """

    def __init__(
        self,
        schedule: FaultSchedule | None,
        identity: str | None = None,
        log_dir: str | None = None,
    ):
        self.schedule = schedule
        self.identity = identity or os.environ.get(
            _ENV_IDENTITY, f"pid:{os.getpid()}"
        )
        self._counters: dict[tuple[str, str], int] = {}
        self._lock = threading.Lock()
        self.events: list[dict] = []
        self._log_fh = None
        log_dir = log_dir or os.environ.get(_ENV_LOG_DIR)
        if schedule is not None and log_dir:
            try:
                os.makedirs(log_dir, exist_ok=True)
                safe = self.identity.replace("/", "_").replace(":", "_")
                self._log_fh = open(
                    os.path.join(log_dir, f"chaos-{safe}-{os.getpid()}.jsonl"),
                    "a",
                    buffering=1,
                )
            except OSError:
                self._log_fh = None
        self._fail_point_hits: dict[str, int] = {}

    # -- state ------------------------------------------------------------
    @property
    def active(self) -> bool:
        return self.schedule is not None

    def elapsed(self) -> float:
        return time.time() - self.schedule.epoch if self.schedule else 0.0

    # -- deterministic decisions ------------------------------------------
    def _roll(self, point: str, method: str) -> tuple[float, int]:
        """A uniform [0,1) draw, pure in (seed, point, method, n)."""
        with self._lock:
            n = self._counters.get((point, method), 0)
            self._counters[(point, method)] = n + 1
        digest = hashlib.sha256(
            f"{self.schedule.seed}|{point}|{method}|{n}".encode()
        ).digest()
        return int.from_bytes(digest[:8], "big") / 2**64, n

    def _record(self, point: str, method: str, n: int, action: str,
                **detail) -> None:
        event = {
            "t": round(self.elapsed(), 4),
            "id": self.identity,
            "point": point,
            "method": method,
            "n": n,
            "action": action,
        }
        if detail:
            event.update(detail)
        self.events.append(event)
        if self._log_fh is not None:
            try:
                self._log_fh.write(json.dumps(event) + "\n")
            except OSError:
                pass

    # -- partitions / slowdowns -------------------------------------------
    def partitioned(self, peer: str | None) -> bool:
        """True while an active partition blocks identity -> peer."""
        if not self.schedule or not self.schedule.partitions:
            return False
        now = self.elapsed()
        for part in self.schedule.partitions:
            start = float(part.get("start_s", 0.0))
            duration = float(part.get("duration_s", 0.0))
            if not (start <= now < start + duration):
                continue
            src_ok = fnmatch.fnmatch(self.identity, part.get("src", "*"))
            dst_ok = peer is not None and fnmatch.fnmatch(
                peer, part.get("dst", "*")
            )
            if src_ok and dst_ok:
                return True
            if part.get("symmetric") and peer is not None:
                if fnmatch.fnmatch(self.identity, part.get("dst", "*")) and \
                        fnmatch.fnmatch(peer, part.get("src", "*")):
                    return True
        return False

    def _slow_extra_ms(self) -> float:
        if not self.schedule or not self.schedule.slow:
            return 0.0
        return sum(
            float(entry.get("extra_ms", 0.0))
            for entry in self.schedule.slow
            if fnmatch.fnmatch(self.identity, entry.get("match", "*"))
        )

    # -- transport hooks ---------------------------------------------------
    async def on_client_send(self, method: str, peer: str | None) -> str:
        """Consulted by both RPC client backends before writing a request
        frame. Sleeps any injected delay; returns "send" or "drop"."""
        schedule = self.schedule
        if schedule is None:
            return "send"
        if self.partitioned(peer):
            # Events are recorded under their ROLL point so the
            # (id, point, method, n) coordinate is unique per decision.
            _, n = self._roll("partition", method)
            self._record("partition", method, n, "partition", peer=peer)
            return "drop"
        if not schedule.targets(method):
            await self._base_delay()
            return "send"
        delay_ms = schedule.delay_ms + self._slow_extra_ms()
        if schedule.delay_jitter_ms > 0:
            jitter, _ = self._roll("delay", method)
            delay_ms += jitter * schedule.delay_jitter_ms
        if schedule.reorder > 0:
            roll, n = self._roll("reorder", method)
            if roll < schedule.reorder:
                # TCP delivers in order per connection; "reorder" = hold
                # this message long enough for later sends to overtake it.
                self._record("reorder", method, n, "reorder")
                delay_ms += schedule.reorder_ms
        if delay_ms > 0:
            await asyncio.sleep(delay_ms / 1000.0)
        roll, n = self._roll("drop_request", method)
        if roll < schedule.drop_request:
            self._record("drop_request", method, n, "drop")
            return "drop"
        return "send"

    async def on_server_request(self, method: str) -> str:
        """Consulted at server dispatch. Returns "dispatch" or "dup"
        (handler deliberately applied twice — the idempotency probe)."""
        schedule = self.schedule
        if schedule is None or not schedule.targets(method):
            return "dispatch"
        roll, n = self._roll("dup_request", method)
        if roll < schedule.dup_request:
            self._record("dup_request", method, n, "dup")
            return "dup"
        return "dispatch"

    async def on_server_reply(self, method: str) -> str:
        """Consulted after the handler ran, before the REP frame is
        written. Returns "send", "drop" (reply lost after the mutation
        applied — the case idempotency tokens exist for) or "dup"."""
        schedule = self.schedule
        if schedule is None or not schedule.targets(method):
            return "send"
        roll, n = self._roll("drop_reply", method)
        if roll < schedule.drop_reply:
            self._record("drop_reply", method, n, "drop")
            return "drop"
        roll, n = self._roll("dup_reply", method)
        if roll < schedule.dup_reply:
            self._record("dup_reply", method, n, "dup")
            return "dup"
        return "send"

    async def _base_delay(self) -> None:
        extra = self._slow_extra_ms()
        if extra > 0:
            await asyncio.sleep(extra / 1000.0)

    # -- chaos-aware call policy ------------------------------------------
    def effective_timeout(self, method: str, timeout: float | None):
        """Cap per-attempt wait so dropped messages surface as timeouts
        instead of hanging the caller forever. Only applies to lossy
        schedules; dups/delays keep the caller's own timeout."""
        if self.schedule is None or not self.schedule.lossy():
            return timeout
        if not self.schedule.targets(method):
            return timeout
        if timeout is None:
            return self.schedule.call_timeout_s
        return min(timeout, self.schedule.call_timeout_s)

    def max_attempts(self, method: str) -> int:
        if self.schedule is None or not self.schedule.lossy():
            return 1
        if not self.schedule.targets(method):
            return 1
        if any(fnmatch.fnmatch(method, m) for m in NON_RETRYABLE):
            return 1
        return max(1, self.schedule.max_call_attempts)

    # -- fail points -------------------------------------------------------
    def failpoint(self, point: str) -> None:
        """Raise ChaosFault while the named fail-point is armed. A count
        of N arms the first N hits; -1 arms it forever."""
        schedule = self.schedule
        if schedule is None:
            return
        budget = schedule.fail_points.get(point)
        if not budget:
            return
        if isinstance(budget, dict):
            now = self.elapsed()
            start = float(budget.get("start_s", 0.0))
            duration = float(budget.get("duration_s", float("inf")))
            if not (start <= now < start + duration):
                return
            budget = int(budget.get("count", -1))
            if not budget:
                return
        hits = self._fail_point_hits.get(point, 0)
        if budget > 0 and hits >= budget:
            return
        self._fail_point_hits[point] = hits + 1
        self._record("failpoint", point, hits, "fail")
        raise ChaosFault(f"injected fault at {point} (hit {hits + 1})")

    def latency_delay(self, point: str) -> float:
        """Extra seconds to sleep at the named latency point (0.0 when
        unarmed). Returns the delay instead of sleeping so async callers
        can await it and sync callers can time.sleep it."""
        schedule = self.schedule
        if schedule is None:
            return 0.0
        extra_ms = schedule.latency_points.get(point, 0.0)
        if isinstance(extra_ms, dict):
            now = self.elapsed()
            start = float(extra_ms.get("start_s", 0.0))
            duration = float(extra_ms.get("duration_s", float("inf")))
            if not (start <= now < start + duration):
                return 0.0
            extra_ms = float(extra_ms.get("extra_ms", 0.0))
        if extra_ms <= 0:
            return 0.0
        self._record("latency_point", point, 0, f"{extra_ms}ms")
        return extra_ms / 1000.0

    def close(self) -> None:
        if self._log_fh is not None:
            try:
                self._log_fh.close()
            except OSError:
                pass
            self._log_fh = None


# ---------------------------------------------------------------------------
# process-wide singleton
# ---------------------------------------------------------------------------
_injector: ChaosInjector | None = None
_injector_lock = threading.Lock()
_NULL = ChaosInjector(None)  # shared inactive injector (zero-alloc fast path)


def _schedule_from_env() -> FaultSchedule | None:
    raw = os.environ.get(_ENV_SCHEDULE)
    if raw:
        if raw.startswith("@"):
            try:
                # rtlint: disable=blocking-in-async - chaos-test fault schedule, read once at first injection when the env var is set; never on a production loop
                with open(raw[1:]) as fh:
                    raw = fh.read()
            except OSError:
                return None
        try:
            return FaultSchedule.from_json(raw)
        except (ValueError, TypeError):
            return None
    # Deprecated alias: a bare uniform RPC delay rides the chaos plane now.
    delay_ms = global_config().testing_rpc_delay_ms
    if delay_ms:
        return FaultSchedule(0, delay_ms=float(delay_ms))
    return None


def get_injector() -> ChaosInjector:
    global _injector
    injector = _injector
    if injector is None:
        with _injector_lock:
            if _injector is None:
                schedule = _schedule_from_env()
                _injector = (
                    ChaosInjector(schedule) if schedule is not None else _NULL
                )
            injector = _injector
    return injector


def install(
    schedule: FaultSchedule | None,
    identity: str | None = None,
    log_dir: str | None = None,
    export_env: bool = True,
) -> ChaosInjector:
    """Install a schedule in THIS process and (by default) export it to
    the environment so cluster subprocesses spawned afterwards inherit
    it. Pass ``schedule=None`` to uninstall."""
    global _injector
    with _injector_lock:
        if _injector is not None:
            _injector.close()
        if export_env:
            if schedule is None:
                os.environ.pop(_ENV_SCHEDULE, None)
                os.environ.pop(_ENV_LOG_DIR, None)
            else:
                os.environ[_ENV_SCHEDULE] = schedule.to_json()
                if log_dir:
                    os.environ[_ENV_LOG_DIR] = log_dir
        _injector = (
            ChaosInjector(schedule, identity=identity, log_dir=log_dir)
            if schedule is not None
            else _NULL
        )
        return _injector


def set_identity(identity: str) -> None:
    """Label this process for partition matching / event attribution
    (controller calls with "controller", agents with "node:<id>", ...).
    Takes effect for the current injector and any future one."""
    os.environ[_ENV_IDENTITY] = identity
    injector = get_injector()
    injector.identity = identity


def reset() -> None:
    """Forget the installed/env-derived injector (tests)."""
    global _injector
    with _injector_lock:
        if _injector is not None:
            _injector.close()
        _injector = None


def failpoint(point: str) -> None:
    """Module-level convenience: subsystems call ``chaos.failpoint(name)``
    at interesting internal boundaries; a no-op unless armed."""
    get_injector().failpoint(point)


def latency_delay(point: str) -> float:
    """Module-level convenience for latency injection points: extra
    seconds to sleep here (0.0 unless armed)."""
    return get_injector().latency_delay(point)

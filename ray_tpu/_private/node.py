"""Cluster process spawning — head-node bootstrap.

Role-equivalent of python/ray/_private/{node.py,services.py} in the
reference: starts the controller (gcs_server-equiv) and node agent
(raylet-equiv) subprocesses, manages the session directory
(/tmp/raytpu/session_*/ with logs + sockets), and tears everything down.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

from ray_tpu._private.ids import NodeID


def _child_env(extra: dict | None = None) -> dict:
    """Child processes must be able to import ray_tpu even when the driver
    loaded it from a source checkout rather than site-packages."""
    env = dict(os.environ)
    package_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    existing = env.get("PYTHONPATH", "")
    if package_root not in existing.split(os.pathsep):
        env["PYTHONPATH"] = (
            package_root + (os.pathsep + existing if existing else "")
        )
    from ray_tpu._private.config import applied_system_config

    system_config = applied_system_config()
    if system_config:
        env["RAYTPU_SYSTEM_CONFIG"] = json.dumps(system_config)
    if extra:
        env.update(extra)
    return env


def new_session_dir() -> str:
    import uuid

    base = os.path.join(tempfile.gettempdir(), "raytpu")
    os.makedirs(base, exist_ok=True)
    # Random suffix: second+pid alone collide when one process creates two
    # clusters within a second (e.g. back-to-back pytest fixtures), which
    # would hand the new cluster the old cluster's stale controller.addr
    # and persisted snapshot.
    session = os.path.join(
        base,
        f"session_{int(time.time())}_{os.getpid()}_{uuid.uuid4().hex[:6]}",
    )
    os.makedirs(session, exist_ok=True)
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    return session


def _wait_for_file(path: str, timeout: float = 120.0) -> str:
    # Generous default: on a loaded single-core host, a fresh subprocess's
    # interpreter+import startup alone can exceed 30s.
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                content = f.read()
            if content.strip():
                return content
        time.sleep(0.02)
    raise TimeoutError(f"timed out waiting for {path}")


class ProcessHandle:
    def __init__(self, proc: subprocess.Popen, name: str):
        self.proc = proc
        self.name = name

    def kill(self) -> None:
        if self.proc.poll() is None:
            # Kill the whole process group: a dead node takes its workers
            # with it (they share the agent's session, set via setsid).
            try:
                os.killpg(self.proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                try:
                    self.proc.send_signal(signal.SIGKILL)
                except ProcessLookupError:
                    pass

    def alive(self) -> bool:
        return self.proc.poll() is None


def start_controller(session_dir: str, port: int = 0) -> tuple[ProcessHandle, tuple]:
    # Drop any stale address file so _wait_for_file can't return the
    # previous controller's port before the new process binds.
    try:
        os.remove(os.path.join(session_dir, "controller.addr"))
    except FileNotFoundError:
        pass
    log = open(os.path.join(session_dir, "logs", "controller.out"), "ab")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "ray_tpu._private.controller",
         "--session-dir", session_dir, "--port", str(port)],
        stdout=log,
        stderr=subprocess.STDOUT,
        env=_child_env(),
        start_new_session=True,
    )
    raw = _wait_for_file(os.path.join(session_dir, "controller.addr"))
    info = json.loads(raw)
    return ProcessHandle(proc, "controller"), (info["host"], info["port"])


def start_node_agent(
    session_dir: str,
    controller_addr: tuple,
    node_id: str | None = None,
    resources: dict | None = None,
    store_capacity: int = 0,
    env: dict | None = None,
) -> tuple[ProcessHandle, tuple, dict, str]:
    node_id = node_id or NodeID.random()
    log = open(
        os.path.join(session_dir, "logs", f"agent-{node_id[-8:]}.out"), "ab"
    )
    spawn_env = _child_env(env)
    proc = subprocess.Popen(
        [
            sys.executable, "-u", "-m", "ray_tpu._private.node_agent",
            "--node-id", node_id,
            "--controller", f"{controller_addr[0]}:{controller_addr[1]}",
            "--session-dir", session_dir,
            "--resources", json.dumps(resources or {}),
            "--store-capacity", str(store_capacity),
        ],
        stdout=log,
        stderr=subprocess.STDOUT,
        env=spawn_env,
        start_new_session=True,
    )
    raw = _wait_for_file(os.path.join(session_dir, f"agent-{node_id[-8:]}.addr"))
    info = json.loads(raw)
    return ProcessHandle(proc, f"agent-{node_id[-8:]}"), tuple(info["addr"]), info["store"], node_id


class LocalCluster:
    """One controller + one or more node agents on this machine."""

    def __init__(self, session_dir: str | None = None):
        self.session_dir = session_dir or new_session_dir()
        self.controller_handle: ProcessHandle | None = None
        self.controller_addr: tuple | None = None
        self.agents: list[ProcessHandle] = []
        # Parallel to self.agents: each agent's RPC address and node id
        # (chaos tooling targets agents by index or node id).
        self.agent_addrs: list[tuple] = []
        self.agent_node_ids: list[str] = []
        self.head_store_info: dict | None = None
        self.head_node_id: str | None = None
        self.head_agent_addr: tuple | None = None
        atexit.register(self.shutdown)

    def start_head(
        self,
        resources: dict | None = None,
        store_capacity: int = 0,
    ) -> None:
        self.controller_handle, self.controller_addr = start_controller(
            self.session_dir
        )
        handle, addr, store, node_id = start_node_agent(
            self.session_dir,
            self.controller_addr,
            resources=resources,
            store_capacity=store_capacity,
        )
        self.agents.append(handle)
        self.agent_addrs.append(addr)
        self.agent_node_ids.append(node_id)
        self.head_agent_addr = addr
        self.head_store_info = store
        self.head_node_id = node_id

    def kill_controller(self) -> None:
        """SIGKILL the control plane (GCS fault-tolerance testing)."""
        if self.controller_handle is not None:
            self.controller_handle.kill()
            self.controller_handle = None

    def restart_controller(self) -> None:
        """Start a fresh controller process on the SAME address; it restores
        state from the session's snapshot and agents/drivers reconnect."""
        assert self.controller_addr is not None, "cluster never started"
        if self.controller_handle is not None:
            self.kill_controller()
        self.controller_handle, self.controller_addr = start_controller(
            self.session_dir, port=self.controller_addr[1]
        )

    def add_node(
        self, resources: dict | None = None, store_capacity: int = 0
    ) -> str:
        handle, addr, store, node_id = start_node_agent(
            self.session_dir, self.controller_addr, resources=resources,
            store_capacity=store_capacity,
        )
        self.agents.append(handle)
        self.agent_addrs.append(addr)
        self.agent_node_ids.append(node_id)
        return node_id

    def shutdown(self) -> None:
        for handle in self.agents:
            handle.kill()
        if self.controller_handle is not None:
            self.controller_handle.kill()
        self.agents = []
        self.controller_handle = None

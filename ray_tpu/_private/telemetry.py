"""Cluster resource-telemetry store (ISSUE 5).

The node agents sample CPU / RSS / object-store / HBM once per
``telemetry_sample_interval_s`` and piggyback the samples on the
heartbeat payload (PR-2 stats channel).  The controller lands them here:
a per-node, bounded, tiered ring buffer with time-based downsampling so
a multi-hour run stays O(MB) —

    raw   : every sample as shipped          (default 360  ≈ 6 min @1s)
    10s   : one bucket per 10 s of samples   (default 360  ≈ 1 h)
    60s   : one bucket per 60 s of samples   (default 1440 ≈ 24 h)

Buckets aggregate **mean** for rate-like gauges (cpu_percent) and
**max** for footprint gauges (rss, mem_used, object-store bytes, hbm):
for capacity planning the peak within a bucket is the signal; averaging
it away would hide short spikes that matter for OOM forensics.

The store is deliberately dependency-free and single-threaded from the
controller's perspective (all mutation happens on the controller's
asyncio thread via rpc_heartbeat), so there are no locks.  Chaos safety:
heartbeats can be duplicated or replayed by the fault layer, so ``add``
drops any sample whose timestamp is not strictly newer than the last one
seen for that node — the series stays monotonic under dup/replay and
bounded under flood.

``project_rss`` is the trend half of the memory monitor's early warning
(satellite of the same PR): a least-squares slope over the recent
(t, rss) history, used by the node agent to emit ``oom_risk`` before the
point-in-time kill threshold fires.
"""

from __future__ import annotations

import collections
from typing import Any, Iterable

# Fields aggregated with max() inside a downsampling bucket; everything
# else numeric is averaged. Footprints peak, rates average.
_MAX_FIELDS = frozenset(
    {
        "mem_used",
        "mem_total",
        "object_store_bytes",
        "object_store_capacity",
        "hbm_used",
        "hbm_total",
        "workers_rss_total",
        "workers_rss_max",
        "num_workers",
    }
)

# Tier name -> bucket width in seconds. "raw" is width 0 (no bucketing).
TIERS: tuple[tuple[str, float], ...] = (("raw", 0.0), ("10s", 10.0), ("60s", 60.0))


def _aggregate(samples: list[dict[str, Any]]) -> dict[str, Any]:
    """Fold a bucket's raw samples into one aggregate sample."""
    if len(samples) == 1:
        return dict(samples[0])
    out: dict[str, Any] = {}
    keys: set[str] = set()
    for s in samples:
        keys.update(s)
    for key in keys:
        vals = [s[key] for s in samples if key in s]
        if key == "ts":
            out["ts"] = max(vals)
        elif all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in vals):
            if key in _MAX_FIELDS:
                out[key] = max(vals)
            else:
                out[key] = sum(vals) / len(vals)
        else:
            out[key] = vals[-1]  # non-numeric (e.g. per-worker map): latest wins
    out["samples"] = sum(int(s.get("samples", 1)) for s in samples)
    return out


class _NodeSeries:
    """All retention tiers for one node."""

    def __init__(self, capacities: dict[str, int]):
        self.rings: dict[str, collections.deque] = {
            name: collections.deque(maxlen=max(1, int(capacities.get(name, 1))))
            for name, _width in TIERS
        }
        # Per-tier open bucket: (bucket_start_epoch, [samples...]).
        self._open: dict[str, tuple[float, list[dict[str, Any]]]] = {}
        self.last_ts: float = 0.0
        self.dropped: int = 0  # non-monotonic (dup/replayed) samples

    def add(self, sample: dict[str, Any]) -> bool:
        ts = sample.get("ts")
        if not isinstance(ts, (int, float)):
            self.dropped += 1
            return False
        if ts <= self.last_ts:  # dup / replay / clock step back: drop
            self.dropped += 1
            return False
        self.last_ts = float(ts)
        self.rings["raw"].append(sample)
        for name, width in TIERS:
            if width <= 0:
                continue
            bucket_start = int(ts // width) * width
            open_bucket = self._open.get(name)
            if open_bucket is None:
                self._open[name] = (bucket_start, [sample])
                continue
            start, pending = open_bucket
            if bucket_start == start:
                pending.append(sample)
            else:
                agg = _aggregate(pending)
                agg["bucket_start"] = start
                agg["bucket_s"] = width
                self.rings[name].append(agg)
                self._open[name] = (bucket_start, [sample])
        return True

    def timeline(self, tier: str | None = None) -> dict[str, list[dict[str, Any]]]:
        """Closed buckets plus a live aggregate of the open bucket, so
        callers (dashboard, `top`) see fresh data without waiting a full
        bucket width."""
        names = [tier] if tier else [name for name, _w in TIERS]
        out: dict[str, list[dict[str, Any]]] = {}
        for name in names:
            if name not in self.rings:
                continue
            points = list(self.rings[name])
            open_bucket = self._open.get(name)
            if open_bucket is not None:
                start, pending = open_bucket
                agg = _aggregate(pending)
                agg["bucket_start"] = start
                agg["partial"] = True
                points.append(agg)
            out[name] = points
        return out

    def latest(self) -> dict[str, Any] | None:
        return self.rings["raw"][-1] if self.rings["raw"] else None


class TelemetryStore:
    """Bounded per-node time-series store living on the controller."""

    def __init__(
        self,
        raw_capacity: int = 360,
        cap_10s: int = 360,
        cap_60s: int = 1440,
        max_nodes: int = 1024,
        max_workload_series: int = 4096,
    ):
        self._caps = {"raw": raw_capacity, "10s": cap_10s, "60s": cap_60s}
        self._max_nodes = max_nodes
        self._nodes: dict[str, _NodeSeries] = {}
        self.total_ingested = 0
        self.total_dropped = 0
        # Workload flight-recorder series (ISSUE 8): same tiered rings +
        # monotonic guard, keyed by series name instead of node id
        # ("train/<exp>", "train/<exp>/rank<k>", "train/<exp>/goodput",
        # "serve/<route>").
        self._max_workload_series = max_workload_series
        self._workloads: dict[str, _NodeSeries] = {}
        self.workload_ingested = 0
        self.workload_dropped = 0

    def add(self, node_id: str, sample: dict[str, Any]) -> bool:
        series = self._nodes.get(node_id)
        if series is None:
            if len(self._nodes) >= self._max_nodes:
                self.total_dropped += 1
                return False
            series = self._nodes[node_id] = _NodeSeries(self._caps)
        ok = series.add(sample)
        if ok:
            self.total_ingested += 1
        else:
            self.total_dropped += 1
        return ok

    def add_many(self, node_id: str, samples: Iterable[dict[str, Any]]) -> int:
        return sum(1 for s in samples if self.add(node_id, s))

    def forget(self, node_id: str) -> None:
        self._nodes.pop(node_id, None)

    def node_ids(self) -> list[str]:
        return sorted(self._nodes)

    def timeline(self, node_id: str, tier: str | None = None) -> dict[str, list]:
        series = self._nodes.get(node_id)
        return series.timeline(tier) if series else {}

    def summary(self) -> dict[str, Any]:
        """Per-node latest sample + series lengths — the payload behind
        ``util/state.summarize_resources()`` and ``ray_tpu top``."""
        nodes: dict[str, Any] = {}
        for node_id, series in self._nodes.items():
            nodes[node_id] = {
                "latest": series.latest(),
                "points": {name: len(ring) for name, ring in series.rings.items()},
                "last_ts": series.last_ts,
                "dropped": series.dropped,
            }
        return {
            "nodes": nodes,
            "total_ingested": self.total_ingested,
            "total_dropped": self.total_dropped,
        }

    # -- workload series (ISSUE 8) --------------------------------------
    def add_workload(self, key: str, sample: dict[str, Any]) -> bool:
        """One flight-recorder sample for series ``key``. Same chaos
        rules as node samples: the ts monotonic guard drops duplicated or
        replayed batches, so a re-delivered round can never double-count
        a step."""
        if not isinstance(key, str) or not key or not isinstance(sample, dict):
            self.workload_dropped += 1
            return False
        series = self._workloads.get(key)
        if series is None:
            if len(self._workloads) >= self._max_workload_series:
                self.workload_dropped += 1
                return False
            series = self._workloads[key] = _NodeSeries(self._caps)
        ok = series.add(sample)
        if ok:
            self.workload_ingested += 1
        else:
            self.workload_dropped += 1
        return ok

    def add_workload_many(
        self, key: str, samples: Iterable[dict[str, Any]]
    ) -> int:
        return sum(1 for s in samples if self.add_workload(key, s))

    def workload_keys(self) -> list[str]:
        return sorted(self._workloads)

    def workload_timeline(
        self, key: str, tier: str | None = None
    ) -> dict[str, list]:
        series = self._workloads.get(key)
        return series.timeline(tier) if series else {}

    def workload_summary(self) -> dict[str, Any]:
        """Per-series latest sample + tier depths — behind
        ``util.state.summarize_workload()`` and ``/api/workload``."""
        series_out: dict[str, Any] = {}
        for key, series in self._workloads.items():
            series_out[key] = {
                "latest": series.latest(),
                "points": {name: len(ring) for name, ring in series.rings.items()},
                "last_ts": series.last_ts,
                "dropped": series.dropped,
            }
        return {
            "series": series_out,
            "total_ingested": self.workload_ingested,
            "total_dropped": self.workload_dropped,
        }

    def stats(self) -> dict[str, int]:
        """Bound-check counters for controller_stats / tests."""
        points = sum(
            len(ring) for s in self._nodes.values() for ring in s.rings.values()
        )
        workload_points = sum(
            len(ring)
            for s in self._workloads.values()
            for ring in s.rings.values()
        )
        return {
            "telemetry_nodes": len(self._nodes),
            "telemetry_points": points,
            "telemetry_ingested": self.total_ingested,
            "telemetry_dropped": self.total_dropped,
            "workload_series": len(self._workloads),
            "workload_points": workload_points,
            "workload_ingested": self.workload_ingested,
            "workload_dropped": self.workload_dropped,
        }


def project_rss(
    history: Iterable[tuple[float, float]], horizon_s: float
) -> float | None:
    """Least-squares RSS projection ``horizon_s`` seconds past the last
    observation.  Returns None when there are <3 points or no time
    spread (a slope from two points is all noise at 1 Hz sampling).

    Used by the node agent's memory monitor: when the projection crosses
    the kill limit while the current RSS is still under it, the worker is
    *trending* toward OOM and an ``oom_risk`` event fires — the early
    warning that a point-in-time threshold can never give.
    """
    pts = [(float(t), float(v)) for t, v in history]
    if len(pts) < 3:
        return None
    t_last = pts[-1][0]
    span = t_last - pts[0][0]
    if span <= 0:
        return None
    n = len(pts)
    mean_t = sum(t for t, _ in pts) / n
    mean_v = sum(v for _, v in pts) / n
    var_t = sum((t - mean_t) ** 2 for t, _ in pts)
    if var_t <= 0:
        return None
    slope = sum((t - mean_t) * (v - mean_v) for t, v in pts) / var_t
    return pts[-1][1] + slope * float(horizon_s)

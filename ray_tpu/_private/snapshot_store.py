"""Pluggable controller persistence backends.

Role-equivalent of the reference's GCS store clients
(src/ray/gcs/store_client/ :: redis_store_client / in_memory_store_client
/ observable_store_client, SURVEY N7): the controller's snapshot logic
writes one opaque blob through a `SnapshotStore`; WHERE it lands is a
deployment choice:

  * ``file``  (default) — atomic write under the session dir; survives
    controller restarts, dies with the head disk.
  * ``memory`` — process-local; tests and throwaway clusters.
  * ``kv://host:port`` — an EXTERNAL wire-v1 KV endpoint (the standalone
    `python -m ray_tpu._private.kv_store_server`, another cluster's
    controller, or anything speaking kv_put/kv_get). Head-disk loss no
    longer loses cluster state: restart the controller anywhere, point it
    at the same store, and it restores (the redis-HA deployment shape).

Selected via RAY_TPU_controller_store (config.controller_store).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time

import msgpack

from ray_tpu._private import atomic_io

_LEN = struct.Struct("<I")
_HDR = struct.Struct("<BBIH")  # ver, kind, msgid, method_len
_SNAPSHOT_NS = "controller_snapshots"
_SNAPSHOT_KEY = "state"


class SnapshotStore:
    # Save instrumentation (class defaults; first write creates instance
    # attrs). The scale suite reads these through controller_stats to
    # prove incremental snapshotting keeps write cost bounded.
    saves = 0
    save_bytes = 0
    save_ms_total = 0.0

    def save(self, blob: bytes) -> None:
        raise NotImplementedError

    def timed_save(self, blob: bytes) -> None:
        """save() plus bookkeeping — the controller's snapshot loop goes
        through here so every backend gets cost accounting for free."""
        start = time.perf_counter()
        self.save(blob)
        self.saves += 1
        self.save_bytes += len(blob)
        self.save_ms_total += (time.perf_counter() - start) * 1e3

    def stats(self) -> dict:
        return {
            "saves": self.saves,
            "bytes": self.save_bytes,
            "ms_total": round(self.save_ms_total, 3),
            "where": self.describe(),
        }

    def load(self) -> bytes | None:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


class FileSnapshotStore(SnapshotStore):
    def __init__(self, path: str):
        self.path = path

    def save(self, blob: bytes) -> None:
        atomic_io.atomic_write_bytes(self.path, blob)

    def load(self) -> bytes | None:
        try:
            with open(self.path, "rb") as fh:
                return fh.read()
        except OSError:
            return None

    def describe(self) -> str:
        return f"file:{self.path}"


class MemorySnapshotStore(SnapshotStore):
    def __init__(self):
        self._blob: bytes | None = None

    def save(self, blob: bytes) -> None:
        self._blob = blob

    def load(self) -> bytes | None:
        return self._blob

    def describe(self) -> str:
        return "memory"


class _SyncWireClient:
    """Minimal BLOCKING wire-v1 client (same framing as the C++ client in
    cpp/src/client.cc): the store is consulted before the controller's
    io loop exists, so persistence cannot ride the asyncio RPC stack."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._sock: socket.socket | None = None
        self._msgid = 0
        self._lock = threading.Lock()

    def _connect(self) -> None:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n > 0:
            chunk = self._sock.recv(n)
            if not chunk:
                raise ConnectionError("kv store connection closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def call(self, method: str, payload: dict) -> dict:
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._sock is None:
                        self._connect()
                    self._msgid += 1
                    body = _HDR.pack(1, 0, self._msgid, len(method))
                    body += method.encode()
                    body += msgpack.packb(payload, use_bin_type=True)
                    self._sock.sendall(_LEN.pack(len(body)) + body)
                    while True:
                        (length,) = _LEN.unpack(self._recv_exact(4))
                        frame = self._recv_exact(length)
                        _ver, kind, msgid, mlen = _HDR.unpack_from(frame, 0)
                        if msgid != self._msgid:
                            continue  # stale/push frame
                        raw = frame[8 + mlen:]
                        reply = (
                            msgpack.unpackb(raw, raw=False) if raw else None
                        )
                        if kind == 2:  # ERR
                            raise RuntimeError(f"kv store error: {reply}")
                        return reply
                except (OSError, ConnectionError):
                    self._sock = None
                    if attempt:
                        raise
        raise ConnectionError("unreachable")


class ExternalKVSnapshotStore(SnapshotStore):
    """Snapshots in an external wire-v1 KV service (redis_store_client
    role). The key is scoped by CLUSTER (session id), so several
    clusters may share one KV endpoint without clobbering each other —
    and a fresh cluster never restores a dead cluster's state. Failures
    raise so the snapshot loop keeps the dirty bit (and boot treats an
    unreachable store as fatal, not empty)."""

    def __init__(self, host: str, port: int, scope: str):
        self._client = _SyncWireClient(host, port)
        self._key = f"{_SNAPSHOT_KEY}:{scope}"
        self._where = f"kv://{host}:{port}/{self._key}"

    def save(self, blob: bytes) -> None:
        reply = self._client.call(
            "kv_put",
            {
                "namespace": _SNAPSHOT_NS,
                "key": self._key,
                "value": blob,
                "overwrite": True,
            },
        )
        if not reply or reply.get("status") != "ok":
            raise RuntimeError(f"external snapshot save failed: {reply}")

    def load(self) -> bytes | None:
        reply = self._client.call(
            "kv_get", {"namespace": _SNAPSHOT_NS, "key": self._key}
        )
        if not reply:
            raise ConnectionError("external snapshot load: empty reply")
        if reply.get("status") != "ok":
            return None  # missing key: genuinely no snapshot for scope
        return reply.get("value")

    def describe(self) -> str:
        return self._where


def make_store(spec: str, session_dir: str) -> SnapshotStore:
    spec = (spec or "file").strip()
    if spec in ("", "file"):
        return FileSnapshotStore(
            os.path.join(session_dir, "controller_state.json")
        )
    if spec == "memory":
        return MemorySnapshotStore()
    if spec.startswith("kv://"):
        hostport = spec[len("kv://"):]
        host, _, port = hostport.rpartition(":")
        scope = os.path.basename(os.path.normpath(session_dir))
        return ExternalKVSnapshotStore(
            host or "127.0.0.1", int(port), scope
        )
    raise ValueError(f"unknown controller store spec {spec!r}")

"""ObjectRef — a distributed future naming an immutable object.

Role-equivalent of the reference ObjectRef (python/ray/_raylet.pyx ObjectRef +
src/ray/common/id.h ObjectID). Holds the owner's address so any holder can
resolve the value (ownership-based object directory,
src/ray/object_manager/ownership_object_directory.cc).
__del__ drives distributed reference counting (reference_count.cc [N21]).
"""

from __future__ import annotations

from typing import Any


class ObjectRef:
    __slots__ = ("id", "owner_address", "_runtime", "__weakref__")

    def __init__(self, object_id: str, owner_address: tuple | None = None, runtime: Any | None = None):
        self.id = object_id
        self.owner_address = tuple(owner_address) if owner_address else None
        self._runtime = runtime
        if runtime is not None:
            runtime.add_local_ref(object_id)

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        assert self._runtime is not None
        return self._runtime.as_future(self)

    def __del__(self):
        runtime = getattr(self, "_runtime", None)
        if runtime is not None:
            try:
                runtime.remove_local_ref(self.id)
            except Exception:  # rtlint: disable=swallowed-exception - __del__ during interpreter teardown
                pass

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id})"

    def __reduce__(self):
        # Plain pickling (outside the runtime serializer) loses the borrow
        # bookkeeping; the runtime serializer intercepts via persistent_id
        # before this is reached.
        return (ObjectRef, (self.id, self.owner_address))

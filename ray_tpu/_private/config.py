"""Runtime flag system.

TPU-native equivalent of the reference's native flag file
(src/ray/common/ray_config_def.h :: RAY_CONFIG macros): one place defining
every runtime knob, each overridable per-process via the environment as
``RAY_TPU_<name>``.  Library-level configs (ScalingConfig etc.) live with
their libraries; this file is the *runtime* tier.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any


def _env(name: str, default: Any) -> Any:
    raw = os.environ.get(f"RAY_TPU_{name}")
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


@dataclasses.dataclass
class RayTpuConfig:
    """All core-runtime knobs. Mirrors ray_config_def.h's role."""

    # --- object plane ---
    # Objects below this size are inlined in RPC replies and live in the
    # owner's in-process memory store (reference:
    # ray_config_def.h :: max_direct_call_object_size ~100KiB).
    max_direct_call_object_size: int = _env("max_direct_call_object_size", 100 * 1024)
    # Shared-memory arena capacity. 0 = auto (30% of system memory, capped).
    object_store_memory: int = _env("object_store_memory", 0)
    object_store_fallback_directory: str = _env(
        "object_store_fallback_directory", ""
    )
    # Chunk size for inter-node object transfer (reference ~5MiB chunks).
    object_transfer_chunk_bytes: int = _env(
        "object_transfer_chunk_bytes", 5 * 1024 * 1024
    )
    # Push-based transfer (push_manager.cc role): owners proactively push
    # large task args toward the consumer's node at submit time; pull
    # stays the fallback. 0 disables.
    push_transfers_enabled: int = _env("push_transfers_enabled", 1)
    push_transfer_min_bytes: int = _env(
        "push_transfer_min_bytes", 1024 * 1024
    )
    # Native lease lane (raylet grant path in C++, N9/N10): the agent's
    # engine grants simple worker leases on its own thread. 0 disables
    # (all leases take the asyncio handler).
    native_lease_lane: int = _env("native_lease_lane", 1)

    # --- health / liveness (reference: health_check_* in ray_config_def.h) ---
    health_check_period_ms: int = _env("health_check_period_ms", 1000)
    health_check_timeout_ms: int = _env("health_check_timeout_ms", 5000)
    health_check_failure_threshold: int = _env("health_check_failure_threshold", 5)

    # --- scheduling ---
    # Above this utilization fraction the hybrid policy stops packing and
    # spreads (reference: scheduler_spread_threshold 0.5).
    scheduler_spread_threshold: float = _env("scheduler_spread_threshold", 0.5)
    # Max number of workers a node agent keeps warm per (runtime_env, lang).
    worker_pool_prestart: int = _env("worker_pool_prestart", 0)
    worker_register_timeout_s: float = _env("worker_register_timeout_s", 60.0)
    # How long a caller waits for a PENDING/RESTARTING actor to come up
    # before failing the call (reference: wait_for_death_info + lease
    # backoff behaviour).
    actor_ready_timeout_s: float = _env("actor_ready_timeout_s", 150.0)
    worker_startup_batch: int = _env("worker_startup_batch", 4)

    # How long a task dispatcher keeps its worker lease warm after its
    # queue drains, waiting for the next same-shape task (reference:
    # normal_task_submitter lease reuse + raylet idle lease grace). Without
    # this every back-to-back sync task pays the full 3-RPC lease chain
    # (controller request_lease + agent lease_worker + dial).
    worker_lease_grace_s: float = _env("worker_lease_grace_s", 0.25)
    # In-flight tasks a dispatcher pipelines through one leased worker
    # before awaiting replies (reference: normal_task_submitter pipelining).
    # Amortizes per-task wakeups/syscalls; 1 = strict request-reply.
    worker_pipeline_depth: int = _env("worker_pipeline_depth", 4)

    # Direct-call lane: simple tasks/actor calls ride the native C++ call
    # table from the caller thread (no asyncio on the hot path —
    # reference: normal_task_submitter.cc direct calls [N19]). Set
    # RAY_TPU_direct_call=0 to force everything through the asyncio path.
    direct_call: bool = _env("direct_call", True)

    # --- memory monitor (reference: memory_monitor.cc + raylet OOM
    # killer, RAY_memory_usage_threshold / RAY_memory_monitor_refresh_ms) ---
    memory_monitor_interval_s: float = _env("memory_monitor_interval_s", 0.25)
    # Node-level usage fraction past which the largest-RSS worker is killed.
    memory_usage_threshold: float = _env("memory_usage_threshold", 0.95)
    # Absolute per-worker RSS cap in MiB (0 = disabled); any worker above
    # it is killed regardless of node usage — also the testing knob.
    memory_worker_rss_limit_mb: int = _env("memory_worker_rss_limit_mb", 0)

    # --- tasks / fault tolerance ---
    task_max_retries_default: int = _env("task_max_retries_default", 3)
    actor_max_restarts_default: int = _env("actor_max_restarts_default", 0)
    lineage_pinning_enabled: bool = _env("lineage_pinning_enabled", True)

    # --- task events / state API (reference: RAY_task_events_max_num_*) ---
    task_events_max_buffer: int = _env("task_events_max_buffer", 10000)

    # --- control-plane persistence (reference: redis_store_client [N7]) ---
    controller_snapshot_period_s: float = _env("controller_snapshot_period_s", 0.5)
    # Snapshot backend: "file" (session dir), "memory", or
    # "kv://host:port" (external wire-v1 KV — survives head-disk loss).
    controller_store: str = _env("controller_store", "file")

    # --- pubsub / rpc ---
    rpc_connect_timeout_s: float = _env("rpc_connect_timeout_s", 30.0)
    rpc_retry_initial_backoff_s: float = _env("rpc_retry_initial_backoff_s", 0.1)
    rpc_retry_max_backoff_s: float = _env("rpc_retry_max_backoff_s", 5.0)
    rpc_retry_max_attempts: int = _env("rpc_retry_max_attempts", 10)

    # --- testing / chaos (reference: RAY_testing_asio_delay_us) ---
    # DEPRECATED alias: kept for compatibility, now interpreted by
    # ray_tpu._private.chaos as a delay-only FaultSchedule applied
    # client-side in both RPC backends. Prefer RAY_TPU_chaos (JSON
    # FaultSchedule) / ray_tpu.util.chaos for anything richer.
    testing_rpc_delay_ms: int = _env("testing_rpc_delay_ms", 0)

    # --- metrics ---
    metrics_report_interval_ms: int = _env("metrics_report_interval_ms", 2000)

    # --- runtime envs (reference: _private/runtime_env/* agent knobs) ---
    # Extra args appended to every `pip install` a node agent runs while
    # materializing a pip runtime env (e.g. "--no-index --find-links /wheels"
    # for airgapped clusters).
    runtime_env_pip_extra_args: str = _env("runtime_env_pip_extra_args", "")
    # Total bytes of unreferenced materialized envs kept cached per node
    # before LRU deletion (reference: RAY_RUNTIME_ENV_*_CACHE_SIZE_GB).
    runtime_env_cache_size_mb: int = _env("runtime_env_cache_size_mb", 2048)
    runtime_env_setup_timeout_s: float = _env(
        "runtime_env_setup_timeout_s", 600.0
    )

    # --- tracing (reference: RAY_TRACING_ENABLED / OTel hook, SURVEY §5.1) ---
    tracing_enabled: bool = _env("tracing_enabled", False)

    # --- resource telemetry (reference: raylet stats + dashboard
    # node_head time-series; Podracer-style sustained-utilization view) ---
    # Master switch for the per-node sampler + controller time-series
    # store. Cheap enough to ship on by default (one psutil sweep per
    # sample interval, piggybacked on the existing heartbeat).
    telemetry_enabled: bool = _env("telemetry_enabled", True)
    # Seconds between node samples. The memory-monitor loop (which runs
    # every memory_monitor_interval_s) assembles a telemetry sample at
    # most this often.
    telemetry_sample_interval_s: float = _env("telemetry_sample_interval_s", 1.0)
    # Ring sizes for the controller store's retention tiers:
    # raw samples (~1 per sample interval), 10s buckets, 60s buckets.
    # Defaults: ~6 min raw + 1 h of 10s + 24 h of 60s per node, all O(MB).
    telemetry_raw_capacity: int = _env("telemetry_raw_capacity", 360)
    telemetry_10s_capacity: int = _env("telemetry_10s_capacity", 360)
    telemetry_60s_capacity: int = _env("telemetry_60s_capacity", 1440)
    # --- workload flight recorder (ISSUE 8) ---
    # Per-step StepStats on train workers (phase breakdown, tokens/FLOPs)
    # + driver-side goodput accounting + serve route histograms. The
    # disabled path is a single attribute check per report/request.
    workload_stats_enabled: bool = _env("workload_stats_enabled", True)
    # Straggler detector: flag ranks persistently > k*MAD above the gang
    # median step time.
    straggler_mad_k: float = _env("straggler_mad_k", 3.0)

    # Trend-aware OOM early warning: emit an ``oom_risk`` event when a
    # worker's RSS slope projects past the kill limit within this horizon
    # (seconds). 0 disables projection.
    oom_risk_horizon_s: float = _env("oom_risk_horizon_s", 10.0)
    # Minimum seconds between oom_risk events for the same worker.
    oom_risk_cooldown_s: float = _env("oom_risk_cooldown_s", 30.0)

    # --- event export (reference: RayEvent export files, N28) ---
    event_export_enabled: bool = _env("event_export_enabled", True)
    event_export_max_bytes: int = _env(
        "event_export_max_bytes", 16 * 1024 * 1024
    )

    # --- TPU topology ---
    # Override autodetected slice topology, e.g. "v4-32". Empty = detect.
    tpu_slice_override: str = _env("tpu_slice_override", "")

    def apply_system_config(self, system_config: dict[str, Any] | None) -> None:
        """Apply a ``_system_config`` dict (reference: ray.init(_system_config=...)).

        The applied dict is remembered so cluster subprocesses can inherit it
        (the reference head propagates _system_config cluster-wide the same
        way)."""
        global _applied_system_config
        if not system_config:
            return
        for key, value in system_config.items():
            if not hasattr(self, key):
                raise ValueError(f"Unknown system config key: {key!r}")
            setattr(self, key, value)
        _applied_system_config.update(system_config)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, raw: str) -> "RayTpuConfig":
        cfg = cls()
        for key, value in json.loads(raw).items():
            setattr(cfg, key, value)
        return cfg


_config: RayTpuConfig | None = None
_applied_system_config: dict[str, Any] = {}


def global_config() -> RayTpuConfig:
    global _config
    if _config is None:
        _config = RayTpuConfig()
        # Subprocesses inherit the driver's _system_config via env.
        inherited = os.environ.get("RAYTPU_SYSTEM_CONFIG")
        if inherited:
            _config.apply_system_config(json.loads(inherited))
    return _config


def applied_system_config() -> dict[str, Any]:
    return dict(_applied_system_config)


def reset_config() -> None:
    global _config
    _config = None
    _applied_system_config.clear()

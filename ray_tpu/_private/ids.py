"""Unique identifiers for objects, tasks, actors, nodes, jobs, workers.

Equivalent of the reference's src/ray/common/id.h (ObjectID/TaskID/ActorID/
NodeID/...). We keep the reference's key structural property: an ObjectID
embeds the TaskID that created it plus a return/put index, which is what makes
lineage-based reconstruction possible (the object's creating task is
recoverable from its id alone).
"""

from __future__ import annotations

import os
import threading

_KIND_PREFIX = {
    "Job": "job",
    "Node": "node",
    "Worker": "wkr",
    "Actor": "act",
    "Task": "tsk",
    "Object": "obj",
    "PlacementGroup": "pg",
    "Gang": "gang",
}


class BaseID(str):
    """Ids are prefixed hex strings — cheap, hashable, msgpack-friendly."""

    KIND = "Base"

    @classmethod
    def random(cls) -> "BaseID":
        return cls(f"{_KIND_PREFIX[cls.KIND]}-{os.urandom(12).hex()}")

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(f"{_KIND_PREFIX[cls.KIND]}-{'0'*24}")

    def is_nil(self) -> bool:
        return self.endswith("0" * 24)


class JobID(BaseID):
    KIND = "Job"


class NodeID(BaseID):
    KIND = "Node"


class WorkerID(BaseID):
    KIND = "Worker"


class ActorID(BaseID):
    KIND = "Actor"


class PlacementGroupID(BaseID):
    KIND = "PlacementGroup"


class GangID(BaseID):
    KIND = "Gang"


class TaskID(BaseID):
    KIND = "Task"

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        return cls(f"tsk-creation-{actor_id}")


class ObjectID(BaseID):
    KIND = "Object"

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        # Embeds the creating task id => lineage reconstruction can find the
        # creating task from the object id (reference: id.h return-id layout).
        return cls(f"obj-{task_id}-r{index}")

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls(f"obj-{task_id}-p{put_index}")

    def creating_task_id(self) -> TaskID | None:
        if self.startswith("obj-tsk-"):
            body = self[len("obj-"):]
            task_part = body.rsplit("-", 1)[0]
            return TaskID(task_part)
        return None

    def is_put(self) -> bool:
        return "-p" in self.rsplit("-", 1)[-1] or self.rsplit("-", 1)[-1].startswith("p")


class _Counter:
    def __init__(self) -> None:
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value

"""Usage/telemetry recording — reference usage_lib role, airgap-first.

Role-equivalent of python/ray/_private/usage/usage_lib.py (SURVEY §2.3):
records which framework features a cluster used. The reference phones
home; this build NEVER transmits — it only merges a local JSON summary
under the session dir (``usage_stats.json``) that operators may inspect
or ship themselves. Disabled entirely with RAY_TPU_usage_stats_enabled=0.
"""

from __future__ import annotations

import fcntl
import json
import os
import threading
import time

from ray_tpu._private import atomic_io

_lock = threading.Lock()
_features: set[str] = set()
_flushed_dir: str | None = None


def enabled() -> bool:
    return os.environ.get("RAY_TPU_usage_stats_enabled", "1").lower() not in (
        "0", "false", "no",
    )


def record_feature(name: str) -> None:
    """Mark a library/feature as used this session (idempotent, cheap)."""
    if not enabled():
        return
    global _flushed_dir
    with _lock:
        session_dir = os.environ.get("RAYTPU_SESSION_DIR")
        # Skip the disk write only when this feature already reached THIS
        # session's file — a long-lived process (test runs, notebooks)
        # crosses init/shutdown cycles and each new session starts empty.
        if name in _features and session_dir == _flushed_dir:
            return
        _features.add(name)
        _flush_locked()
        _flushed_dir = session_dir


def _flush_locked() -> None:
    session_dir = os.environ.get("RAYTPU_SESSION_DIR")
    if not session_dir:
        return
    path = os.path.join(session_dir, "usage_stats.json")
    # Merge-on-write: several processes (driver, trial/train workers)
    # share the session file. The read-merge-write must be one critical
    # section (flock on a sidecar) and the write must land atomically
    # (temp + os.replace) so concurrent flushers can't drop each other's
    # features and readers never observe torn JSON.
    try:
        lock_fh = open(path + ".lock", "a")
    except OSError:
        return
    try:
        try:
            fcntl.flock(lock_fh, fcntl.LOCK_EX)
        except OSError:
            # No lock service (NFS without lockd, ENOLCK): fall back to
            # unserialized merge — telemetry must never crash user code.
            pass
        merged = set(_features)
        try:
            with open(path) as fh:
                merged.update(json.load(fh).get("features", []))
        except (OSError, json.JSONDecodeError):
            pass
        try:
            atomic_io.atomic_write_json(
                path,
                {
                    "features": sorted(merged),
                    "updated_at": time.time(),
                    "transmitted": False,  # never — local record only
                },
            )
        except OSError:  # rtlint: disable=swallowed-exception - telemetry must never crash user code
            pass
    finally:
        try:
            fcntl.flock(lock_fh, fcntl.LOCK_UN)
        except OSError:
            pass
        lock_fh.close()


def read(session_dir: str) -> dict:
    try:
        with open(os.path.join(session_dir, "usage_stats.json")) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return {"features": [], "transmitted": False}

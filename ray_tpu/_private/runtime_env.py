"""Per-node runtime-environment materialization.

Role-equivalent of the reference's runtime-env agent
(python/ray/_private/runtime_env/{agent,pip,working_dir,py_modules,plugin}.py):
before a worker starts under a runtime env, the node agent materializes
each plugin's resources into a per-node cache keyed by content URI, with
reference counting per job and LRU deletion of unreferenced entries.

Design differences from the reference (deliberate, documented):

- The manager runs **inside the node agent's process** instead of a
  sidecar agent process. Our node agent is already an asyncio daemon and
  the materialization work (pip subprocess, file copies) runs off-loop in
  a thread executor, so a separate process buys nothing here.
- URIs are content hashes computed locally (``pip://<sha1-of-reqs>``,
  ``pydir://<sha1-of-tree>``), not GCS-uploaded packages: every node can
  reach the job's submitted working_dir through the controller KV if it
  is remote, and local paths are the common case in tests and single-host
  clusters.

Plugins implemented: ``env_vars``, ``working_dir``, ``pip``,
``py_modules``. Unknown keys raise, matching the reference's validation.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import shutil
import sys
import time
import zipfile
from dataclasses import dataclass, field

from ray_tpu._private.config import global_config
from ray_tpu import exceptions

KNOWN_FIELDS = {
    "env_vars",
    "working_dir",
    "pip",
    "py_modules",
    "config",
}


def validate_runtime_env(runtime_env: dict | None) -> dict:
    env = dict(runtime_env or {})
    unknown = set(env) - KNOWN_FIELDS
    if unknown:
        raise ValueError(
            f"Unknown runtime_env field(s) {sorted(unknown)}; "
            f"supported: {sorted(KNOWN_FIELDS)}"
        )
    if "pip" in env and env["pip"] is not None:
        pip = env["pip"]
        if isinstance(pip, str):
            env["pip"] = [pip]
        elif isinstance(pip, dict):
            env["pip"] = list(pip.get("packages", []))
        elif not isinstance(pip, (list, tuple)):
            raise ValueError("runtime_env['pip'] must be a list / str / dict")
    if "py_modules" in env and env["py_modules"] is not None:
        if not isinstance(env["py_modules"], (list, tuple)):
            raise ValueError("runtime_env['py_modules'] must be a list")
    return env


def _hash_tree(path: str) -> str:
    """Content hash of a file or directory tree (names + bytes)."""
    digest = hashlib.sha1()
    if os.path.isfile(path):
        digest.update(os.path.basename(path).encode())
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                digest.update(chunk)
        return digest.hexdigest()
    for root, dirs, files in os.walk(path):
        dirs.sort()
        # __pycache__ churns between runs without semantic change.
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in sorted(files):
            full = os.path.join(root, name)
            digest.update(os.path.relpath(full, path).encode())
            try:
                with open(full, "rb") as fh:
                    for chunk in iter(lambda: fh.read(1 << 20), b""):
                        digest.update(chunk)
            except OSError:
                continue
    return digest.hexdigest()


def _publish_dir(tmp: str, target: str) -> None:
    """Atomically publish a staged dir; another process winning the same
    content-addressed target is equivalent — discard ours and use theirs."""
    try:
        os.replace(tmp, target)
    except OSError:
        if os.path.isdir(target):
            shutil.rmtree(tmp, ignore_errors=True)
        else:
            raise


def _dir_size(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return total


@dataclass
class CacheEntry:
    uri: str
    path: str
    size: int = 0
    refs: set = field(default_factory=set)  # job ids
    last_used: float = field(default_factory=time.monotonic)


@dataclass
class EnvContext:
    """What a materialized runtime env contributes to a worker spawn."""

    env_vars: dict = field(default_factory=dict)
    python_paths: list = field(default_factory=list)
    working_dir: str | None = None
    uris: list = field(default_factory=list)


class RuntimeEnvManager:
    """Materializes runtime envs into ``<session_dir>/runtime_env/``.

    Concurrency: ``setup`` may be called for many workers at once; per-URI
    creation is single-flighted through an asyncio lock so two workers
    needing the same pip env trigger one install.
    """

    def __init__(self, session_dir: str):
        self.root = os.path.join(session_dir, "runtime_env")
        os.makedirs(self.root, exist_ok=True)
        self._cache: dict[str, CacheEntry] = {}
        self._locks: dict[str, asyncio.Lock] = {}
        self.stats = {"hits": 0, "misses": 0, "evictions": 0}

    # -- public ---------------------------------------------------------
    async def setup(self, runtime_env: dict | None, job_id: str) -> EnvContext:
        env = validate_runtime_env(runtime_env)
        ctx = EnvContext()
        ctx.env_vars = {
            str(k): str(v) for k, v in (env.get("env_vars") or {}).items()
        }
        timeout = global_config().runtime_env_setup_timeout_s
        try:
            if env.get("pip"):
                entry = await asyncio.wait_for(
                    self._get_or_create_pip(list(env["pip"]), job_id), timeout
                )
                ctx.python_paths.append(entry.path)
                ctx.uris.append(entry.uri)
            for module in env.get("py_modules") or []:
                entry = await asyncio.wait_for(
                    self._get_or_create_py_module(str(module), job_id), timeout
                )
                ctx.python_paths.append(entry.path)
                ctx.uris.append(entry.uri)
            working_dir = env.get("working_dir")
            if working_dir:
                if str(working_dir).endswith(".zip"):
                    entry = await asyncio.wait_for(
                        self._get_or_create_zip_dir(str(working_dir), job_id),
                        timeout,
                    )
                    ctx.working_dir = entry.path
                    ctx.uris.append(entry.uri)
                else:
                    # Plain directories are used in place (single-host /
                    # shared-filesystem case; also what the existing
                    # working_dir tests rely on).
                    ctx.working_dir = str(working_dir)
        except asyncio.TimeoutError:
            raise exceptions.RuntimeEnvSetupError(
                f"runtime env setup timed out after {timeout:.0f}s: {env}"
            )
        return ctx

    def release_job(self, job_id: str) -> None:
        """Drop ``job_id``'s references; GC unreferenced entries over cap."""
        for entry in self._cache.values():
            entry.refs.discard(job_id)
        self._evict_over_cap()

    def cache_info(self) -> dict:
        return {
            "entries": [
                {
                    "uri": e.uri,
                    "path": e.path,
                    "size": e.size,
                    "refs": sorted(e.refs),
                }
                for e in self._cache.values()
            ],
            **self.stats,
        }

    # -- plugin creation ------------------------------------------------
    async def _single_flight(self, uri: str, job_id: str, create) -> CacheEntry:
        lock = self._locks.setdefault(uri, asyncio.Lock())
        async with lock:
            entry = self._cache.get(uri)
            if entry is not None and os.path.isdir(entry.path):
                self.stats["hits"] += 1
            else:
                self.stats["misses"] += 1
                path = await create()
                entry = CacheEntry(uri=uri, path=path, size=_dir_size(path))
                self._cache[uri] = entry
            entry.refs.add(job_id)
            entry.last_used = time.monotonic()
            return entry

    async def _get_or_create_pip(
        self, reqs: list[str], job_id: str
    ) -> CacheEntry:
        digest = hashlib.sha1("\n".join(sorted(reqs)).encode()).hexdigest()
        uri = f"pip://{digest}"
        target = os.path.join(self.root, "pip", digest)

        async def create() -> str:
            # Per-process staging dir: node agents are separate processes
            # sharing one session dir, so a shared tmp path would let one
            # agent rmtree another's in-progress install.
            tmp = f"{target}.installing.{os.getpid()}"
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp, exist_ok=True)
            cmd = [
                sys.executable, "-m", "pip", "install",
                "--quiet", "--no-input", "--disable-pip-version-check",
                "--target", tmp,
            ]
            extra = global_config().runtime_env_pip_extra_args.split()
            cmd += extra + list(reqs)
            proc = await asyncio.create_subprocess_exec(
                *cmd,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT,
            )
            try:
                out, _ = await proc.communicate()
            except asyncio.CancelledError:
                # setup() timeout cancelled us: kill pip so a retry's
                # rmtree can't race a still-running install into a
                # corrupt cached env.
                proc.kill()
                try:
                    await proc.wait()
                except Exception:  # rtlint: disable=swallowed-exception - pip already reaped after kill
                    pass
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            if proc.returncode != 0:
                shutil.rmtree(tmp, ignore_errors=True)
                raise exceptions.RuntimeEnvSetupError(
                    f"pip install failed for {reqs}:\n"
                    + out.decode(errors="replace")[-4000:]
                )
            _publish_dir(tmp, target)
            return target

        return await self._single_flight(uri, job_id, create)

    async def _get_or_create_py_module(
        self, module_path: str, job_id: str
    ) -> CacheEntry:
        if not os.path.exists(module_path):
            raise exceptions.RuntimeEnvSetupError(
                f"py_modules entry does not exist: {module_path}"
            )
        digest = await asyncio.get_running_loop().run_in_executor(
            None, _hash_tree, module_path
        )
        uri = f"pydir://{digest}"
        target = os.path.join(self.root, "py_modules", digest)

        async def create() -> str:
            def stage() -> str:
                tmp = f"{target}.staging.{os.getpid()}"
                shutil.rmtree(tmp, ignore_errors=True)
                os.makedirs(tmp, exist_ok=True)
                if module_path.endswith(".zip"):
                    with zipfile.ZipFile(module_path) as zf:
                        zf.extractall(tmp)
                else:
                    # The *parent* goes on sys.path; stage the module dir
                    # under its own name (reference py_modules semantics).
                    name = os.path.basename(module_path.rstrip("/"))
                    shutil.copytree(module_path, os.path.join(tmp, name))
                _publish_dir(tmp, target)
                return target

            return await asyncio.get_running_loop().run_in_executor(None, stage)

        return await self._single_flight(uri, job_id, create)

    async def _get_or_create_zip_dir(
        self, zip_path: str, job_id: str
    ) -> CacheEntry:
        if not os.path.exists(zip_path):
            raise exceptions.RuntimeEnvSetupError(
                f"working_dir zip does not exist: {zip_path}"
            )
        digest = await asyncio.get_running_loop().run_in_executor(
            None, _hash_tree, zip_path
        )
        uri = f"workdir://{digest}"
        target = os.path.join(self.root, "working_dir", digest)

        async def create() -> str:
            def stage() -> str:
                tmp = f"{target}.staging.{os.getpid()}"
                shutil.rmtree(tmp, ignore_errors=True)
                os.makedirs(tmp, exist_ok=True)
                with zipfile.ZipFile(zip_path) as zf:
                    zf.extractall(tmp)
                _publish_dir(tmp, target)
                return target

            return await asyncio.get_running_loop().run_in_executor(None, stage)

        return await self._single_flight(uri, job_id, create)

    # -- GC -------------------------------------------------------------
    def _evict_over_cap(self) -> None:
        cap = global_config().runtime_env_cache_size_mb * 1024 * 1024
        unreferenced = [e for e in self._cache.values() if not e.refs]
        total = sum(e.size for e in self._cache.values())
        unreferenced.sort(key=lambda e: e.last_used)
        for entry in unreferenced:
            if total <= cap:
                break
            shutil.rmtree(entry.path, ignore_errors=True)
            self._cache.pop(entry.uri, None)
            self._locks.pop(entry.uri, None)
            total -= entry.size
            self.stats["evictions"] += 1

"""Python client for the native shared-memory object store.

Role-equivalent of plasma's client
(reference: src/ray/object_manager/plasma/client.cc and the core worker's
store_provider/plasma_store_provider.cc). Object *bytes* never traverse the
socket: clients mmap the arena file once and read/write through memoryviews
(zero-copy); only control messages (create/seal/get/...) use the socket.
"""

from __future__ import annotations

import mmap
import os
import socket
import struct
import threading

from ray_tpu import _native

OP_CREATE, OP_SEAL, OP_GET, OP_RELEASE, OP_DELETE = 1, 2, 3, 4, 5
OP_CONTAINS, OP_LIST, OP_STATS, OP_PIN, OP_UNPIN = 6, 7, 8, 9, 10
ST_OK, ST_NOT_FOUND, ST_FULL, ST_EXISTS, ST_TIMEOUT, ST_ERROR = range(6)

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")


class ObjectStoreFull(Exception):
    pass


class ObjectStoreServer:
    """Owns the native store server thread (lives in the node agent)."""

    def __init__(
        self,
        socket_path: str,
        shm_path: str,
        capacity: int,
        spill_dir: str | None = None,
    ):
        self._lib = _native.load()
        self._handle = self._lib.raytpu_store_start(
            socket_path.encode(),
            shm_path.encode(),
            capacity,
            (spill_dir or "").encode(),
        )
        if not self._handle:
            raise RuntimeError(f"failed to start object store at {socket_path}")
        self.socket_path = socket_path
        self.shm_path = shm_path
        self.capacity = capacity

    def stop(self) -> None:
        if self._handle:
            self._lib.raytpu_store_stop(self._handle)
            self._handle = None


class ObjectStoreClient:
    """Thread-safe synchronous client; one per process is typical."""

    def __init__(self, socket_path: str, shm_path: str, capacity: int):
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.connect(socket_path)
        self._lock = threading.Lock()
        self._reqid = 0
        shm_fd = os.open(shm_path, os.O_RDWR)
        try:
            self._arena = mmap.mmap(shm_fd, capacity, mmap.MAP_SHARED)
        finally:
            os.close(shm_fd)
        self._view = memoryview(self._arena)

    # -- protocol helpers --------------------------------------------------
    def _request(self, op: int, payload: bytes) -> tuple[int, bytes]:
        with self._lock:
            self._reqid += 1
            reqid = self._reqid
            frame = _U32.pack(reqid) + bytes([op]) + payload
            self._sock.sendall(_U32.pack(len(frame)) + frame)
            while True:
                reply = self._recv_frame()
                (rid,) = _U32.unpack_from(reply, 0)
                status = reply[4]
                if rid == reqid:
                    return status, reply[5:]
                # Stale reply from an abandoned (timed-out) request: skip.

    def _recv_frame(self) -> bytes:
        header = self._recv_exact(4)
        (length,) = _U32.unpack(header)
        return self._recv_exact(length)

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n > 0:
            chunk = self._sock.recv(n)
            if not chunk:
                raise ConnectionError("object store connection closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    @staticmethod
    def _enc_id(object_id: str) -> bytes:
        raw = object_id.encode()
        return struct.pack("<H", len(raw)) + raw

    # -- public API --------------------------------------------------------
    def create(self, object_id: str, size: int) -> memoryview:
        """Allocate; returns a writable view. Call seal() when done."""
        status, payload = self._request(
            OP_CREATE, self._enc_id(object_id) + _U64.pack(size)
        )
        if status == ST_FULL:
            raise ObjectStoreFull(f"store full creating {object_id} ({size}B)")
        if status == ST_EXISTS:
            raise FileExistsError(object_id)
        if status != ST_OK:
            raise RuntimeError(f"create({object_id}) failed: status={status}")
        (offset,) = _U64.unpack_from(payload, 0)
        # Trailing byte: server-committed ("warm") flag — see _touch_pages.
        if len(payload) > 8 and payload[8]:
            self._touch_pages(offset, size)
        return self._view[offset : offset + size]

    def _touch_pages(self, offset: int, size: int) -> None:
        """Read-fault one byte per page of a fresh allocation BEFORE the
        caller's bulk copy. A strided vectorized read populates this
        process's PTEs for ~0.06 µs/page; without it the copy itself eats a
        write-fault per 4 KiB (~0.4 ms/MiB measured on 1-core hosts, 4×
        the memcpy). Pairs with the server's prefault thread, which keeps
        the underlying tmpfs pages committed ahead of the allocator."""
        if size < (1 << 20):
            return  # fault cost is negligible below ~1 MiB
        try:
            import numpy as np

            np.frombuffer(self._arena, np.uint8, size, offset)[::4096].max()
        except Exception:
            view = self._view
            for off in range(offset, offset + size, 4096):
                view[off]

    def seal(self, object_id: str) -> None:
        status, _ = self._request(OP_SEAL, self._enc_id(object_id))
        if status != ST_OK:
            raise RuntimeError(f"seal({object_id}) failed: status={status}")

    def put(self, object_id: str, data: bytes | memoryview) -> None:
        buf = self.create(object_id, len(data))
        buf[:] = data
        self.seal(object_id)

    def get(self, object_id: str, timeout_ms: int = -1) -> memoryview | None:
        """Zero-copy read view, or None on timeout/absent (timeout_ms=0)."""
        status, payload = self._request(
            OP_GET, self._enc_id(object_id) + _I64.pack(timeout_ms)
        )
        if status in (ST_NOT_FOUND, ST_TIMEOUT):
            return None
        if status != ST_OK:
            raise RuntimeError(f"get({object_id}) failed: status={status}")
        offset, size = _U64.unpack_from(payload, 0)[0], _U64.unpack_from(payload, 8)[0]
        return self._view[offset : offset + size].toreadonly()

    def release(self, object_id: str) -> None:
        self._request(OP_RELEASE, self._enc_id(object_id))

    def delete(self, object_id: str) -> bool:
        status, _ = self._request(OP_DELETE, self._enc_id(object_id))
        return status == ST_OK

    def contains(self, object_id: str) -> bool:
        status, _ = self._request(OP_CONTAINS, self._enc_id(object_id))
        return status == ST_OK

    def pin(self, object_id: str) -> None:
        self._request(OP_PIN, self._enc_id(object_id))

    def unpin(self, object_id: str) -> None:
        self._request(OP_UNPIN, self._enc_id(object_id))

    def list(self) -> dict[str, dict]:
        status, payload = self._request(OP_LIST, b"")
        if status != ST_OK:
            return {}
        (count,) = _U64.unpack_from(payload, 0)
        pos = 8
        out: dict[str, dict] = {}
        for _ in range(count):
            (idlen,) = struct.unpack_from("<H", payload, pos)
            pos += 2
            object_id = payload[pos : pos + idlen].decode()
            pos += idlen
            size, flags, refcount = struct.unpack_from("<QQQ", payload, pos)
            pos += 24
            out[object_id] = {
                "size": size,
                "sealed": bool(flags & 1),
                "spilled": bool(flags & 2),
                "refcount": refcount,
            }
        return out

    def stats(self) -> dict:
        status, payload = self._request(OP_STATS, b"")
        if status != ST_OK:
            raise RuntimeError("stats failed")
        capacity, used, num_objects, spilled, evictions, restores = struct.unpack(
            "<6Q", payload
        )
        return {
            "capacity": capacity,
            "used": used,
            "num_objects": num_objects,
            "spilled_bytes": spilled,
            "evictions": evictions,
            "restores": restores,
        }

    def close(self) -> None:
        try:
            self._sock.close()
        except Exception:  # rtlint: disable=swallowed-exception - close of an already-dead socket
            pass

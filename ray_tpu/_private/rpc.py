"""Control-plane RPC transport.

Role-equivalent of the reference's typed async gRPC wrappers
(src/ray/rpc/ :: GrpcServer/ServerCall/ClientCallManager + retryable
clients).

Wire format v1 — a versioned binary envelope (the typed-schema role of the
reference's protobuf layer, N14) with msgpack payloads:

    [u32 frame_len][u8 ver=1][u8 kind][u32 msgid][u16 method_len]
    [method bytes][msgpack payload]

kind: 0=request, 1=reply, 2=error-reply, 3=push (server->client, no reply).

Two interchangeable backends speak this format:

  * **native** (default): ``src/rpc/transport.cc`` — a C++ epoll engine per
    event loop owns every socket, does framing/parsing/write batching in
    native code, and hands whole decoded messages to asyncio through one
    eventfd-notified inbox. Measured ~30 us/RTT vs ~105 us for the asyncio
    path on the same host.
  * **asyncio** fallback (``RAY_TPU_native_rpc=0`` or native build
    failure): pure-Python StreamReader/Writer framing.

Features mirrored from the reference RPC layer:
  - per-call async completion (ClientCallManager)
  - retry with full-jitter exponential backoff on connect failure
    (retryable clients; jitter breaks the thundering herd of every client
    redialing on the identical schedule after a controller crash)
  - server push over an established connection (used by pubsub, §N8)
  - deterministic fault injection (``ray_tpu._private.chaos``): a seeded
    FaultSchedule can drop/delay/duplicate/reorder individual messages and
    partition identity pairs at both the client send point and the server
    dispatch/reply points. The legacy RAY_TPU_testing_rpc_delay_ms knob
    (RAY_testing_asio_delay_us twin) is a deprecated alias for a
    delay-only schedule, now applied uniformly in BOTH client backends.
"""

from __future__ import annotations

import asyncio
import ctypes
import itertools
import os
import random
import struct
import threading
import time
import traceback
from typing import Any, Awaitable, Callable

import msgpack

from ray_tpu._private import chaos
from ray_tpu._private.config import global_config
from ray_tpu.util.backoff import Backoff

REQ, REP, ERR, PUSH = 0, 1, 2, 3
ACCEPTED, CLOSED = 254, 255  # synthetic engine events, never on the wire
_LEN = struct.Struct("<I")
_HDR = struct.Struct("<BBIH")  # ver, kind, msgid, method_len
WIRE_VERSION = 1

Handler = Callable[..., Awaitable[Any]]


# Strong references to fire-and-forget tasks: asyncio's loop only weakly
# references tasks, so an unreferenced create_task() can be GC'd mid-flight
# (silently dropping an RPC dispatch or a scheduler coroutine). Every
# fire-and-forget task in the runtime goes through spawn_task().
_BG_TASKS: set = set()


def spawn_task(coro) -> "asyncio.Task":
    task = asyncio.get_running_loop().create_task(coro)
    _BG_TASKS.add(task)
    task.add_done_callback(_BG_TASKS.discard)
    return task


class RpcError(Exception):
    """Remote handler raised; carries the remote traceback string."""


class ConnectionLost(Exception):
    pass


def _encode_payload(payload: Any) -> bytes:
    return msgpack.packb(payload, use_bin_type=True)


def _decode_payload(raw: bytes) -> Any:
    if not raw:
        return None
    return msgpack.unpackb(raw, raw=False, strict_map_key=False)


def _pack(kind: int, msgid: int, method: str, payload: Any) -> bytes:
    m = method.encode()
    p = _encode_payload(payload)
    return (
        _LEN.pack(_HDR.size + len(m) + len(p))
        + _HDR.pack(WIRE_VERSION, kind, msgid, len(m))
        + m
        + p
    )


async def _read_frame(reader: asyncio.StreamReader) -> tuple[int, int, str, Any]:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    body = await reader.readexactly(length)
    _ver, kind, msgid, mlen = _HDR.unpack_from(body, 0)
    method = body[_HDR.size : _HDR.size + mlen].decode()
    payload = _decode_payload(body[_HDR.size + mlen :])
    return kind, msgid, method, payload


# ---------------------------------------------------------------------------
# Backend selection
# ---------------------------------------------------------------------------
_NATIVE_OK: bool | None = None


def native_available() -> bool:
    global _NATIVE_OK
    if _NATIVE_OK is None:
        if os.environ.get("RAY_TPU_native_rpc", "1").lower() in ("0", "false", "no"):
            _NATIVE_OK = False
        else:
            try:
                from ray_tpu import _native

                _native.load()
                _NATIVE_OK = True
            except Exception:
                _NATIVE_OK = False
    return _NATIVE_OK


def _rpc_debug(message: str) -> None:
    """RAY_TPU_debug_rpc=1: append transport-level events (accepts, drops,
    closes) to /tmp/raytpu_rpc_debug.log — forensics for lost-frame bugs."""
    if not os.environ.get("RAY_TPU_debug_rpc"):
        return
    try:
        # rtlint: disable=blocking-in-async - opt-in forensics behind RAY_TPU_debug_rpc; one appended line per event, only while actively debugging lost frames
        with open("/tmp/raytpu_rpc_debug.log", "a") as fh:
            fh.write(f"{os.getpid()} {time.time():.3f} {message}\n")
    except OSError:
        pass


# ---------------------------------------------------------------------------
# Native engine (one per event loop)
# ---------------------------------------------------------------------------
class _NativeEngine:
    """Python face of one C++ epoll engine bound to one asyncio loop.

    The engine's notify eventfd is registered with loop.add_reader; _drain
    runs on the loop thread and routes each decoded message to its owning
    client/server-connection object — the only per-message Python work is
    the route + payload decode, no stream parsing."""

    _by_loop: dict[int, "_NativeEngine"] = {}
    _lock = threading.Lock()

    @classmethod
    def for_running_loop(cls) -> "_NativeEngine":
        loop = asyncio.get_running_loop()
        with cls._lock:
            engine = cls._by_loop.get(id(loop))
            if engine is None:
                engine = cls(loop)
                cls._by_loop[id(loop)] = engine
        return engine

    @classmethod
    def destroy_for_loop(cls, loop) -> None:
        with cls._lock:
            engine = cls._by_loop.pop(id(loop), None)
        if engine is not None:
            engine.stop()

    def __init__(self, loop):
        from ray_tpu import _native

        self.lib = _native.load()
        # GIL-keeping handle for the microsecond-scale non-blocking calls
        # (send/next/msgid/free): avoids a GIL release+reacquire per
        # message, which dominates the call cost under thread contention.
        self.pylib = _native.load_nogilrelease()
        self.RtMsgView = _native.RtMsgView
        self.handle = self.lib.rt_engine_new()
        self.loop = loop
        self.notify_fd = self.lib.rt_notify_fd(self.handle)
        # conn_id -> owner (NativeRpcClient | NativeServerConnection)
        self.owners: dict[int, Any] = {}
        # listener conn_id -> NativeRpcServer
        self.listeners: dict[int, "NativeRpcServer"] = {}
        loop.add_reader(self.notify_fd, self._drain)
        _rpc_debug(f"engine-created eng={id(self):x} loop={id(loop):x} notify_fd={self.notify_fd}")

    def stop(self) -> None:
        _rpc_debug(f"engine-stopped eng={id(self):x}")
        try:
            self.loop.remove_reader(self.notify_fd)
        except Exception:  # rtlint: disable=swallowed-exception - reader may already be removed from a dead loop
            pass
        if self.handle:
            self.lib.rt_engine_stop(self.handle)
            self.handle = None

    # Above this, use the GIL-releasing handle: the inline write of a big
    # frame (and any wait on the connection's write mutex behind it) must
    # not stall every Python thread.
    _PYLIB_MAX_PAYLOAD = 64 * 1024

    def send(self, conn: int, kind: int, msgid: int, method: bytes,
             payload: bytes) -> int:
        if not self.handle:
            # Engine already destroyed (loop teardown): a queued dispatch
            # callback may still try to write its reply. Passing the NULL
            # handle into rt_send is a segfault; fail the send instead so
            # the caller takes its ConnectionError path.
            return -1
        lib = (
            self.pylib if len(payload) < self._PYLIB_MAX_PAYLOAD else self.lib
        )
        return lib.rt_send(
            self.handle, conn, kind, msgid, method, len(method), payload,
            len(payload),
        )

    def close_conn(self, conn: int) -> None:
        if self.handle:
            self.lib.rt_close_conn(self.handle, conn)

    def stats(self) -> dict:
        """Internal engine counters (frames/bytes/chunks/queue depths) —
        the N27 observability surface for everything native."""
        if not self.handle:
            return {}
        out = (ctypes.c_longlong * 12)()
        self.lib.rt_engine_stats(self.handle, out)
        return {
            "frames_sent": int(out[0]),
            "frames_received": int(out[1]),
            "bytes_sent": int(out[2]),
            "bytes_received": int(out[3]),
            "chunks_sent": int(out[4]),
            "chunks_received": int(out[5]),
            "inbox_depth": int(out[6]),
            "exec_queue_depth": int(out[7]),
            "write_queue_frames": int(out[8]),
            "connections": int(out[9]),
            "lease_grants": int(out[10]),
            "calls_inflight": int(out[11]),
        }

    def _drain(self) -> None:
        if not self.handle:
            return  # destroyed while this callback was already queued
        try:
            os.read(self.notify_fd, 8)
        except (BlockingIOError, OSError):
            pass
        lib = self.pylib
        while True:
            view = self.RtMsgView()
            if not lib.rt_next(self.handle, ctypes.byref(view)):
                break
            kind = view.kind
            conn = view.conn
            msgid = view.msgid
            method = (
                ctypes.string_at(view.method, view.mlen).decode()
                if view.mlen
                else ""
            )
            raw = (
                ctypes.string_at(view.payload, view.plen) if view.plen else b""
            )
            lib.rt_msg_free(view.opaque)
            if kind == ACCEPTED:
                server = self.listeners.get(msgid)
                if server is not None:
                    server._on_accept(conn)
                    _rpc_debug(f"accept conn={conn} listener={msgid}")
                else:
                    _rpc_debug(f"accept-NO-LISTENER conn={conn} l={msgid}")
                    self.close_conn(conn)
                continue
            owner = self.owners.get(conn)
            if owner is not None:
                if kind == REQ:
                    _rpc_debug(
                        f"recv-req conn={conn} msgid={msgid} m={method} "
                        f"eng={id(self):x}"
                    )
                owner._on_native_msg(kind, msgid, method, raw)
            elif kind != CLOSED:
                # A REQ/REP for a conn with no owner means the peer still
                # believes this connection is alive — dropping silently
                # would black-hole its calls forever (each side keeps an
                # ESTABLISHED socket and waits). Close the conn so the peer
                # observes ConnectionLost and retries/redials.
                import sys as _sys

                print(
                    f"[raytpu-rpc] no owner for conn={conn} "
                    f"kind={kind} method={method!r} — closing the conn",
                    file=_sys.stderr,
                )
                _rpc_debug(
                    f"DROP+close conn={conn} kind={kind} method={method!r}"
                )
                self.close_conn(conn)


# ---------------------------------------------------------------------------
# Server side
# ---------------------------------------------------------------------------
class _ServerDispatchMixin:
    """Shared handler-dispatch semantics for both backends."""

    name: str
    _handlers: dict

    def route(self, method: str, handler: Handler) -> None:
        self._handlers[method] = handler

    def route_push(self, channel: str, handler) -> None:
        """Register an async handler(conn, raw_payload) for PUSH frames
        arriving at this server (no reply is sent)."""
        self._push_handlers[channel] = handler

    def route_object(self, obj: Any, prefix: str = "") -> None:
        """Register every ``rpc_<name>`` coroutine method of obj as <name>."""
        for attr in dir(obj):
            if attr.startswith("rpc_"):
                self.route(prefix + attr[4:], getattr(obj, attr))

    async def _dispatch(self, conn, msgid: int, method: str, payload: Any) -> None:
        injector = chaos.get_injector()
        handler = self._handlers.get(method)
        try:
            if handler is None:
                raise RpcError(f"no handler for method {method!r} on {self.name}")
            if injector.active:
                # Duplicated request: deliberately run the handler twice —
                # the idempotency probe for mutation RPCs. Only the reply
                # to the second application is sent (the client popped its
                # future on the first REP anyway).
                if await injector.on_server_request(method) == "dup":
                    await handler(conn, payload)
                result = await handler(conn, payload)
                reply_fate = await injector.on_server_reply(method)
                if reply_fate == "drop":
                    # Reply lost AFTER the mutation applied — the classic
                    # retry-after-dropped-ack case idempotency tokens
                    # exist for. The caller times out and re-sends.
                    return
                await conn.send(REP, msgid, method, result)
                if reply_fate == "dup":
                    await conn.send(REP, msgid, method, result)
                return
            result = await handler(conn, payload)
            await conn.send(REP, msgid, method, result)
        except ConnectionError:
            # The reply could not be written — nothing more to tell the peer.
            conn.closed.set()
        except Exception:
            # Handler raised (including RuntimeError): the caller MUST get an
            # ERR reply. Swallowing handler errors here once black-holed
            # every push_task whose _load_callable raised — the caller's
            # future then waited forever on a healthy connection.
            try:
                await conn.send(ERR, msgid, method, traceback.format_exc())
            except Exception:
                conn.closed.set()


class NativeServerConnection:
    """One accepted connection owned by the native engine."""

    def __init__(self, engine: _NativeEngine, conn_id: int, server):
        self.engine = engine
        self.conn_id = conn_id
        self._server = server
        self.closed = asyncio.Event()
        self.context: dict[str, Any] = {}

    async def send(self, kind: int, msgid: int, method: str, payload: Any) -> None:
        rc = self.engine.send(
            self.conn_id, kind, msgid, method.encode(), _encode_payload(payload)
        )
        if rc != 0:
            raise ConnectionError(f"send to conn {self.conn_id} failed ({rc})")

    async def push(self, channel: str, payload: Any) -> None:
        try:
            await self.send(PUSH, 0, channel, payload)
        except (ConnectionError, RuntimeError):
            self.closed.set()

    def _on_native_msg(self, kind: int, msgid: int, method: str, raw: bytes) -> None:
        if kind == CLOSED:
            _rpc_debug(f"server-conn-closed conn={self.conn_id}")
            self.engine.owners.pop(self.conn_id, None)
            self.closed.set()
            server = self._server
            if server is not None:
                server.connections.discard(self)
                if server.on_disconnect is not None:
                    spawn_task(server._run_disconnect(self))
            return
        if kind == REQ:
            spawn_task(self._server._dispatch(self, msgid, method,
                                              _decode_payload(raw)))
            return
        if kind == PUSH:
            # Engine-originated notifications (e.g. obj_complete from the
            # C++ object-transfer plane) and peer pushes toward a server.
            handler = self._server._push_handlers.get(method)
            if handler is not None:
                spawn_task(handler(self, raw))
        # REP/ERR toward a server connection have no meaning here.


class NativeRpcServer(_ServerDispatchMixin):
    """RPC server backed by the C++ epoll engine."""

    def __init__(self, name: str = "rpc"):
        self.name = name
        self._handlers: dict[str, Handler] = {}
        self._push_handlers: dict[str, Handler] = {}
        self.connections: set[NativeServerConnection] = set()
        self.on_disconnect: Callable[[Any], Awaitable[None]] | None = None
        self._engine: _NativeEngine | None = None
        self._listener_ids: list[int] = []

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._engine = _NativeEngine.for_running_loop()
        out_port = ctypes.c_int(0)
        lid = self._engine.lib.rt_listen_tcp(
            self._engine.handle, host.encode(), port, ctypes.byref(out_port)
        )
        if lid < 0:
            raise OSError(-lid, f"cannot listen on {host}:{port}")
        self._engine.listeners[lid] = self
        self._listener_ids.append(lid)
        return out_port.value

    async def start_unix(self, path: str) -> None:
        self._engine = _NativeEngine.for_running_loop()
        lid = self._engine.lib.rt_listen_unix(self._engine.handle, path.encode())
        if lid < 0:
            raise OSError(-lid, f"cannot listen on {path}")
        self._engine.listeners[lid] = self
        self._listener_ids.append(lid)

    async def stop(self) -> None:
        if self._engine is None:
            return
        for lid in self._listener_ids:
            self._engine.listeners.pop(lid, None)
            self._engine.close_conn(lid)
        self._listener_ids.clear()
        for conn in list(self.connections):
            self._engine.close_conn(conn.conn_id)

    def _on_accept(self, conn_id: int) -> None:
        conn = NativeServerConnection(self._engine, conn_id, self)
        self.connections.add(conn)
        self._engine.owners[conn_id] = conn

    async def _run_disconnect(self, conn) -> None:
        try:
            await self.on_disconnect(conn)
        except Exception:
            traceback.print_exc()


class AsyncioServerConnection:
    """One accepted client connection; lets handlers push to this client."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._write_lock = asyncio.Lock()
        self.closed = asyncio.Event()
        # Server-side scratch: handlers stash identity here (e.g. node id
        # after a Register call) so disconnect cleanup knows who died.
        self.context: dict[str, Any] = {}

    async def send(self, kind: int, msgid: int, method: str, payload: Any) -> None:
        async with self._write_lock:
            self.writer.write(_pack(kind, msgid, method, payload))
            await self.writer.drain()

    async def push(self, channel: str, payload: Any) -> None:
        try:
            await self.send(PUSH, 0, channel, payload)
        except (ConnectionError, RuntimeError):
            self.closed.set()


class AsyncioRpcServer(_ServerDispatchMixin):
    """Pure-asyncio RPC server (fallback backend)."""

    def __init__(self, name: str = "rpc"):
        self.name = name
        self._handlers: dict[str, Handler] = {}
        self._push_handlers: dict[str, Handler] = {}
        self._server: asyncio.AbstractServer | None = None
        self.connections: set[AsyncioServerConnection] = set()
        self.on_disconnect: Callable[[Any], Awaitable[None]] | None = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._on_client, host, port)
        return self._server.sockets[0].getsockname()[1]

    async def start_unix(self, path: str) -> None:
        self._server = await asyncio.start_unix_server(self._on_client, path)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self.connections):
            try:
                conn.writer.close()
            except Exception:  # rtlint: disable=swallowed-exception - closing client conns at server stop
                pass

    async def _on_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn = AsyncioServerConnection(reader, writer)
        self.connections.add(conn)
        try:
            while True:
                kind, msgid, method, payload = await _read_frame(reader)
                if kind != REQ:
                    continue
                spawn_task(self._dispatch(conn, msgid, method, payload))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self.connections.discard(conn)
            conn.closed.set()
            if self.on_disconnect is not None:
                try:
                    await self.on_disconnect(conn)
                except Exception:
                    traceback.print_exc()
            try:
                writer.close()
            except Exception:  # rtlint: disable=swallowed-exception - peer already closed the transport
                pass


# ---------------------------------------------------------------------------
# Client side
# ---------------------------------------------------------------------------
class _ClientCallMixin:
    """Shared call/retry/push semantics for both client backends.

    With ``auto_reconnect=True`` a call on a dropped connection first
    redials (exponential backoff) and then runs ``on_reconnect`` — the
    hook re-plays registration/subscription handshakes, which is how
    agents and workers survive a controller restart (role-equivalent of
    the reference's gcs_client reconnect, SURVEY §5.3)."""

    def _init_common(self, address, name, auto_reconnect) -> None:
        self.address = address
        self.name = name
        self.auto_reconnect = auto_reconnect
        # Total call() invocations over this client's lifetime — the
        # rtdag zero-RPC-per-step acceptance gate reads the delta across
        # a window of steady-state executes. The per-method split names
        # whatever a nonzero delta was (steady-state probes report it so
        # a stray background call is attributable, not just counted).
        self.calls_total = 0
        self.calls_by_method: dict[str, int] = {}
        self.on_reconnect: Callable[[], Awaitable[None]] | None = None
        self._reconnect_lock: asyncio.Lock | None = None
        self._closed = False
        self._pending: dict[int, asyncio.Future] = {}
        self._push_handlers: dict[str, Callable[[Any], Awaitable[None] | None]] = {}
        self.connected = False
        # Chaos identity of the REMOTE end ("controller", "node:<id>", ...)
        # — consulted for asymmetric partition matching. None = unmatched
        # by partitions (message-level faults still apply).
        self.chaos_peer: str | None = None

    def on_push(self, channel: str, handler: Callable[[Any], Any]) -> None:
        self._push_handlers[channel] = handler

    async def _ensure_connected(self) -> None:
        if self.connected or self._closed:
            return
        if self._reconnect_lock is None:
            self._reconnect_lock = asyncio.Lock()
        async with self._reconnect_lock:
            if self.connected or self._closed:
                return
            await self.connect(retry=True)
            if self.on_reconnect is not None:
                # Replay the session handshake (connected is already True,
                # so the hook's own calls go straight through).
                await self.on_reconnect()

    async def call(
        self,
        method: str,
        payload: Any = None,
        timeout: float | None = None,
        on_sent: Callable[[], None] | None = None,
    ) -> Any:
        # Auto-reconnect clients retry ONCE after a connection loss: the
        # first call racing a server restart may be written to the dying
        # socket and surface ConnectionLost even though the new server is
        # already up. ``on_sent`` fires synchronously once the request
        # frame is on the wire — callers that must order their writes
        # (actor sequence numbers) release the next writer from it while
        # still awaiting this reply concurrently.
        self.calls_total += 1
        self.calls_by_method[method] = (
            self.calls_by_method.get(method, 0) + 1
        )
        injector = chaos.get_injector()
        if injector.active:
            return await self._call_with_chaos(
                injector, method, payload, timeout, on_sent
            )
        for attempt in (0, 1):
            if not self.connected:
                if self.auto_reconnect and not self._closed:
                    await self._ensure_connected()
                else:
                    raise ConnectionLost(f"{self.name}: not connected")
            try:
                return await self._call_once(method, payload, timeout, on_sent)
            except ConnectionLost:
                if not self.auto_reconnect or self._closed or attempt:
                    raise

    async def _call_with_chaos(
        self,
        injector,
        method: str,
        payload: Any,
        timeout: float | None,
        on_sent: Callable[[], None] | None,
    ) -> Any:
        """Chaos-active twin of call(): each attempt's wait is capped (a
        dropped message must surface as a timeout, not an eternal hang)
        and retryable methods are re-sent up to the schedule's budget —
        which is exactly what makes dropped-reply idempotency real."""
        eff_timeout = injector.effective_timeout(method, timeout)
        attempts = injector.max_attempts(method)
        # The plain path's contract: auto_reconnect clients survive ONE
        # ConnectionLost per call. Chaos may add retry budget on top but
        # must never take that away (attempts==1 for delay-only schedules
        # and non-retryable methods).
        conn_budget = 1 if self.auto_reconnect else 0
        attempt = 0
        last_exc: Exception | None = None
        while attempt < attempts:
            if self._closed:
                raise ConnectionLost(f"{self.name}: closed")
            if not self.connected:
                if self.auto_reconnect:
                    await self._ensure_connected()
                else:
                    raise ConnectionLost(f"{self.name}: not connected")
            fate = await injector.on_client_send(method, self.chaos_peer)
            if fate == "drop":
                # Swallowed by the "network": emulate the wait the caller
                # would experience before its timeout fires.
                wait = (
                    eff_timeout
                    if eff_timeout is not None
                    else injector.schedule.call_timeout_s
                )
                await asyncio.sleep(wait)
                last_exc = asyncio.TimeoutError(
                    f"{self.name}: {method} lost to chaos (attempt {attempt})"
                )
                attempt += 1
                continue
            try:
                return await self._call_once(method, payload, eff_timeout,
                                             on_sent)
            except asyncio.TimeoutError as exc:
                last_exc = exc
                attempt += 1
            except ConnectionLost as exc:
                last_exc = exc
                if not self.auto_reconnect or self._closed:
                    raise
                if conn_budget > 0:
                    conn_budget -= 1  # free retry, as in the plain path
                else:
                    attempt += 1
        raise last_exc if last_exc is not None else ConnectionLost(
            f"{self.name}: {method} exhausted chaos retries"
        )

    def _fail_pending(self) -> None:
        for future in self._pending.values():
            if not future.done():
                future.set_exception(
                    ConnectionLost(f"{self.name} lost connection")
                )
        self._pending.clear()

    def _handle_push(self, method: str, payload: Any) -> None:
        if method == "__pub_batch__":
            # Controller-side pubsub batching (one push frame per
            # connection per tick): demux back into per-channel handlers
            # in publish order.
            for item in payload:
                self._handle_push(item[0], item[1])
            return
        handler = self._push_handlers.get(method)
        if handler is not None:
            result = handler(payload)
            if asyncio.iscoroutine(result):
                spawn_task(result)

    def _resolve(self, kind: int, msgid: int, payload: Any) -> None:
        future = self._pending.pop(msgid, None)
        if future is None or future.done():
            return
        if kind == REP:
            future.set_result(payload)
        else:
            future.set_exception(RpcError(payload))


class NativeRpcClient(_ClientCallMixin):
    """RPC client backed by the C++ epoll engine."""

    def __init__(
        self,
        address: tuple[str, int] | str,
        name: str = "client",
        auto_reconnect: bool = False,
    ):
        self._init_common(address, name, auto_reconnect)
        self._engine: _NativeEngine | None = None
        self._conn_id: int | None = None

    async def connect(self, retry: bool = True) -> None:
        cfg = global_config()
        backoff = Backoff(
            initial_backoff_s=cfg.rpc_retry_initial_backoff_s,
            max_backoff_s=cfg.rpc_retry_max_backoff_s,
        )
        attempts = cfg.rpc_retry_max_attempts if retry else 1
        engine = _NativeEngine.for_running_loop()
        last_err = 0
        for _ in range(attempts):
            if isinstance(self.address, str):
                conn = engine.lib.rt_connect_unix(
                    engine.handle, self.address.encode()
                )
            else:
                host, port = self.address
                conn = engine.lib.rt_connect_tcp(
                    engine.handle, str(host).encode(), int(port)
                )
            if conn > 0:
                self._engine = engine
                self._conn_id = conn
                engine.owners[conn] = self
                self.connected = True
                _rpc_debug(f"dial ok conn={conn} addr={self.address} name={self.name} eng={id(engine):x}")
                return
            last_err = -conn
            # Full jitter (AWS-style): otherwise every client orphaned by a
            # controller crash redials on the identical schedule, and the
            # restarted server eats a synchronized thundering herd.
            await backoff.async_sleep()
        raise ConnectionLost(
            f"{self.name}: cannot connect to {self.address}: errno {last_err}"
        )

    def _on_native_msg(self, kind: int, msgid: int, method: str, raw: bytes) -> None:
        if kind == CLOSED:
            _rpc_debug(f"client-conn-closed conn={self._conn_id} addr={self.address}")
            self.connected = False
            if self._engine is not None:
                self._engine.owners.pop(self._conn_id, None)
            self._conn_id = None
            self._fail_pending()
            return
        if kind == PUSH:
            self._handle_push(method, _decode_payload(raw))
            return
        self._resolve(kind, msgid, _decode_payload(raw))

    async def _call_once(
        self, method: str, payload: Any, timeout: float | None,
        on_sent: Callable[[], None] | None = None,
    ) -> Any:
        engine, conn = self._engine, self._conn_id
        if engine is None or conn is None:
            raise ConnectionLost(f"{self.name}: not connected")
        msgid = engine.pylib.rt_next_msgid(engine.handle, conn)
        if msgid == 0:
            self.connected = False
            raise ConnectionLost(f"{self.name}: connection gone")
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[msgid] = future
        rc = engine.send(conn, REQ, msgid, method.encode(),
                         _encode_payload(payload))
        _rpc_debug(
            f"send conn={conn} msgid={msgid} m={method} rc={rc} eng={id(engine):x}"
        )
        if rc != 0:
            self._pending.pop(msgid, None)
            self.connected = False
            raise ConnectionLost(f"{self.name}: send failed ({rc})")
        if on_sent is not None:
            on_sent()
        if timeout is None:
            return await future
        return await asyncio.wait_for(future, timeout)

    async def close(self) -> None:
        self._closed = True
        self.connected = False
        if self._engine is not None and self._conn_id is not None:
            self._engine.owners.pop(self._conn_id, None)
            self._engine.close_conn(self._conn_id)
            self._conn_id = None
        self._fail_pending()


class AsyncioRpcClient(_ClientCallMixin):
    """Pure-asyncio RPC client (fallback backend)."""

    def __init__(
        self,
        address: tuple[str, int] | str,
        name: str = "client",
        auto_reconnect: bool = False,
    ):
        self._init_common(address, name, auto_reconnect)
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._msgids = itertools.count(1)
        self._write_lock: asyncio.Lock | None = None
        self._recv_task: asyncio.Task | None = None

    async def connect(self, retry: bool = True) -> None:
        cfg = global_config()
        backoff = Backoff(
            initial_backoff_s=cfg.rpc_retry_initial_backoff_s,
            max_backoff_s=cfg.rpc_retry_max_backoff_s,
        )
        attempts = cfg.rpc_retry_max_attempts if retry else 1
        last_exc: Exception | None = None
        for _ in range(attempts):
            try:
                if isinstance(self.address, str):
                    self._reader, self._writer = await asyncio.open_unix_connection(
                        self.address
                    )
                else:
                    self._reader, self._writer = await asyncio.open_connection(
                        *self.address
                    )
                self._write_lock = asyncio.Lock()
                self._recv_task = asyncio.get_running_loop().create_task(
                    self._recv_loop()
                )
                self.connected = True
                return
            except (ConnectionError, OSError) as exc:
                last_exc = exc
                # Full jitter, mirroring the native backend: break the
                # post-crash redial herd.
                await backoff.async_sleep()
        raise ConnectionLost(
            f"{self.name}: cannot connect to {self.address}: {last_exc}"
        )

    async def _recv_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                kind, msgid, method, payload = await _read_frame(self._reader)
                if kind == PUSH:
                    self._handle_push(method, payload)
                    continue
                self._resolve(kind, msgid, payload)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self.connected = False
            self._fail_pending()

    async def _call_once(
        self, method: str, payload: Any, timeout: float | None,
        on_sent: Callable[[], None] | None = None,
    ) -> Any:
        msgid = next(self._msgids)
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[msgid] = future
        assert self._writer is not None and self._write_lock is not None
        try:
            async with self._write_lock:
                self._writer.write(_pack(REQ, msgid, method, payload))
                await self._writer.drain()
        except (ConnectionError, OSError) as exc:
            self._pending.pop(msgid, None)
            self.connected = False
            raise ConnectionLost(f"{self.name}: send failed: {exc}")
        if on_sent is not None:
            on_sent()
        if timeout is None:
            return await future
        return await asyncio.wait_for(future, timeout)

    async def close(self) -> None:
        self._closed = True
        self.connected = False
        if self._recv_task is not None:
            self._recv_task.cancel()
            # Await the cancellation so the loop reaps the task — otherwise
            # teardown prints "Task was destroyed but it is pending!" for
            # every client's recv loop (r2 verdict weak #3). asyncio.wait
            # absorbs the task's CancelledError without swallowing a
            # cancellation aimed at close() itself.
            await asyncio.wait({self._recv_task})
            self._recv_task = None
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:  # rtlint: disable=swallowed-exception - transport already closed
                pass


# ---------------------------------------------------------------------------
# Backend-picking constructors (public names used across the runtime)
# ---------------------------------------------------------------------------
def RpcServer(name: str = "rpc"):
    if native_available():
        return NativeRpcServer(name)
    return AsyncioRpcServer(name)


def RpcClient(
    address: tuple[str, int] | str,
    name: str = "client",
    auto_reconnect: bool = False,
):
    if native_available():
        return NativeRpcClient(address, name, auto_reconnect)
    return AsyncioRpcClient(address, name, auto_reconnect)


# Annotation alias: handlers type their ``conn`` argument with this.
ServerConnection = AsyncioServerConnection


class IoThread:
    """Background asyncio loop thread: the driver/worker 'io service'.

    Equivalent in role to the core worker's io_service threads
    (reference: core_worker.cc io_service_). Sync API code schedules
    coroutines here via run().
    """

    def __init__(self, name: str = "raytpu-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro: Awaitable[Any], timeout: float | None = None) -> Any:
        future = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return future.result(timeout)

    def spawn(self, coro: Awaitable[Any]) -> None:
        asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self) -> None:
        async def _shutdown() -> None:
            tasks = [
                t for t in asyncio.all_tasks(self.loop)
                if t is not asyncio.current_task()
            ]
            for task in tasks:
                task.cancel()
            # Await the cancellations so every task is reaped before the
            # loop stops — a bare call_soon(stop) races the cancellation
            # delivery and leaves "Task was destroyed but it is pending!"
            # warnings behind (r2 verdict weak #3). Bounded: a task whose
            # cleanup awaits something slow (e.g. a retry-backoff dial)
            # must not pin the loop open past the join timeout.
            if tasks:
                await asyncio.wait(tasks, timeout=1.5)
            _NativeEngine.destroy_for_loop(self.loop)
            self.loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(_shutdown(), self.loop)
            self._thread.join(timeout=2)
        except Exception:  # rtlint: disable=swallowed-exception - loop already stopped at interpreter exit
            pass

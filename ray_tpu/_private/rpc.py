"""Control-plane RPC transport.

Role-equivalent of the reference's typed async gRPC wrappers
(src/ray/rpc/ :: GrpcServer/ServerCall/ClientCallManager + retryable clients).
We use length-prefixed msgpack frames over asyncio TCP/unix sockets: compact,
zero-dependency, and fast enough for a control plane (bulk data rides the
shared-memory object store, never this channel).

Frame layout (msgpack array):
    [kind, msgid, method, payload]
kind: 0=request, 1=reply, 2=error-reply, 3=push (server->client, no reply).

Features mirrored from the reference RPC layer:
  - per-call async completion (ClientCallManager)
  - retry with exponential backoff on connect failure (retryable clients)
  - server push over an established connection (used by pubsub, §N8)
  - optional injected delay for chaos tests (RAY_testing_asio_delay_us twin:
    RAY_TPU_testing_rpc_delay_ms).
"""

from __future__ import annotations

import asyncio
import itertools
import struct
import threading
import traceback
from typing import Any, Awaitable, Callable

import msgpack

from ray_tpu._private.config import global_config

REQ, REP, ERR, PUSH = 0, 1, 2, 3
_LEN = struct.Struct("<I")

Handler = Callable[..., Awaitable[Any]]


# Strong references to fire-and-forget tasks: asyncio's loop only weakly
# references tasks, so an unreferenced create_task() can be GC'd mid-flight
# (silently dropping an RPC dispatch or a scheduler coroutine). Every
# fire-and-forget task in the runtime goes through spawn_task().
_BG_TASKS: set = set()


def spawn_task(coro) -> "asyncio.Task":
    task = asyncio.get_running_loop().create_task(coro)
    _BG_TASKS.add(task)
    task.add_done_callback(_BG_TASKS.discard)
    return task


class RpcError(Exception):
    """Remote handler raised; carries the remote traceback string."""


class ConnectionLost(Exception):
    pass


def _pack(kind: int, msgid: int, method: str, payload: Any) -> bytes:
    body = msgpack.packb((kind, msgid, method, payload), use_bin_type=True)
    return _LEN.pack(len(body)) + body


async def _read_frame(reader: asyncio.StreamReader) -> tuple[int, int, str, Any]:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    body = await reader.readexactly(length)
    return tuple(msgpack.unpackb(body, raw=False, strict_map_key=False))


class ServerConnection:
    """One accepted client connection; lets handlers push to this client."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._write_lock = asyncio.Lock()
        self.closed = asyncio.Event()
        # Server-side scratch: handlers stash identity here (e.g. node id
        # after a Register call) so disconnect cleanup knows who died.
        self.context: dict[str, Any] = {}

    async def send(self, kind: int, msgid: int, method: str, payload: Any) -> None:
        async with self._write_lock:
            self.writer.write(_pack(kind, msgid, method, payload))
            await self.writer.drain()

    async def push(self, channel: str, payload: Any) -> None:
        try:
            await self.send(PUSH, 0, channel, payload)
        except (ConnectionError, RuntimeError):
            self.closed.set()


class RpcServer:
    """Asyncio RPC server. Handlers are async callables(conn, payload)."""

    def __init__(self, name: str = "rpc"):
        self.name = name
        self._handlers: dict[str, Handler] = {}
        self._server: asyncio.AbstractServer | None = None
        self.connections: set[ServerConnection] = set()
        self.on_disconnect: Callable[[ServerConnection], Awaitable[None]] | None = None

    def route(self, method: str, handler: Handler) -> None:
        self._handlers[method] = handler

    def route_object(self, obj: Any, prefix: str = "") -> None:
        """Register every ``rpc_<name>`` coroutine method of obj as <name>."""
        for attr in dir(obj):
            if attr.startswith("rpc_"):
                self.route(prefix + attr[4:], getattr(obj, attr))

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._on_client, host, port)
        return self._server.sockets[0].getsockname()[1]

    async def start_unix(self, path: str) -> None:
        self._server = await asyncio.start_unix_server(self._on_client, path)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self.connections):
            try:
                conn.writer.close()
            except Exception:
                pass

    async def _on_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn = ServerConnection(reader, writer)
        self.connections.add(conn)
        try:
            while True:
                kind, msgid, method, payload = await _read_frame(reader)
                if kind != REQ:
                    continue
                spawn_task(self._dispatch(conn, msgid, method, payload))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self.connections.discard(conn)
            conn.closed.set()
            if self.on_disconnect is not None:
                try:
                    await self.on_disconnect(conn)
                except Exception:
                    traceback.print_exc()
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(
        self, conn: ServerConnection, msgid: int, method: str, payload: Any
    ) -> None:
        delay_ms = global_config().testing_rpc_delay_ms
        if delay_ms:
            await asyncio.sleep(delay_ms / 1000.0)
        handler = self._handlers.get(method)
        try:
            if handler is None:
                raise RpcError(f"no handler for method {method!r} on {self.name}")
            result = await handler(conn, payload)
            await conn.send(REP, msgid, method, result)
        except (ConnectionError, RuntimeError):
            conn.closed.set()
        except Exception:
            try:
                await conn.send(ERR, msgid, method, traceback.format_exc())
            except Exception:
                conn.closed.set()


class RpcClient:
    """Async RPC client with reconnect/backoff and push subscription.

    With ``auto_reconnect=True`` a call on a dropped connection first
    redials (exponential backoff) and then runs ``on_reconnect`` — the
    hook re-plays registration/subscription handshakes, which is how
    agents and workers survive a controller restart (role-equivalent of
    the reference's gcs_client reconnect, SURVEY §5.3)."""

    def __init__(
        self,
        address: tuple[str, int] | str,
        name: str = "client",
        auto_reconnect: bool = False,
    ):
        self.address = address
        self.name = name
        self.auto_reconnect = auto_reconnect
        self.on_reconnect: Callable[[], Awaitable[None]] | None = None
        self._reconnect_lock: asyncio.Lock | None = None
        self._closed = False
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._msgids = itertools.count(1)
        self._write_lock: asyncio.Lock | None = None
        self._recv_task: asyncio.Task | None = None
        self._push_handlers: dict[str, Callable[[Any], Awaitable[None] | None]] = {}
        self.connected = False

    async def _ensure_connected(self) -> None:
        if self.connected or self._closed:
            return
        if self._reconnect_lock is None:
            self._reconnect_lock = asyncio.Lock()
        async with self._reconnect_lock:
            if self.connected or self._closed:
                return
            await self.connect(retry=True)
            if self.on_reconnect is not None:
                # Replay the session handshake (connected is already True,
                # so the hook's own calls go straight through).
                await self.on_reconnect()

    def on_push(self, channel: str, handler: Callable[[Any], Any]) -> None:
        self._push_handlers[channel] = handler

    async def connect(self, retry: bool = True) -> None:
        cfg = global_config()
        backoff = cfg.rpc_retry_initial_backoff_s
        attempts = cfg.rpc_retry_max_attempts if retry else 1
        last_exc: Exception | None = None
        for _ in range(attempts):
            try:
                if isinstance(self.address, str):
                    self._reader, self._writer = await asyncio.open_unix_connection(
                        self.address
                    )
                else:
                    self._reader, self._writer = await asyncio.open_connection(
                        *self.address
                    )
                self._write_lock = asyncio.Lock()
                self._recv_task = asyncio.get_running_loop().create_task(
                    self._recv_loop()
                )
                self.connected = True
                return
            except (ConnectionError, OSError) as exc:
                last_exc = exc
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, cfg.rpc_retry_max_backoff_s)
        raise ConnectionLost(
            f"{self.name}: cannot connect to {self.address}: {last_exc}"
        )

    async def _recv_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                kind, msgid, method, payload = await _read_frame(self._reader)
                if kind == PUSH:
                    handler = self._push_handlers.get(method)
                    if handler is not None:
                        result = handler(payload)
                        if asyncio.iscoroutine(result):
                            spawn_task(result)
                    continue
                future = self._pending.pop(msgid, None)
                if future is None or future.done():
                    continue
                if kind == REP:
                    future.set_result(payload)
                else:
                    future.set_exception(RpcError(payload))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self.connected = False
            for future in self._pending.values():
                if not future.done():
                    future.set_exception(ConnectionLost(f"{self.name} lost connection"))
            self._pending.clear()

    async def call(self, method: str, payload: Any = None, timeout: float | None = None) -> Any:
        # Auto-reconnect clients retry ONCE after a connection loss: the
        # first call racing a server restart may be written to the dying
        # socket and surface ConnectionLost even though the new server is
        # already up.
        for attempt in (0, 1):
            if not self.connected:
                if self.auto_reconnect and not self._closed:
                    await self._ensure_connected()
                else:
                    raise ConnectionLost(f"{self.name}: not connected")
            try:
                return await self._call_once(method, payload, timeout)
            except ConnectionLost:
                if not self.auto_reconnect or self._closed or attempt:
                    raise

    async def _call_once(self, method: str, payload: Any, timeout: float | None) -> Any:
        msgid = next(self._msgids)
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[msgid] = future
        assert self._writer is not None and self._write_lock is not None
        try:
            async with self._write_lock:
                self._writer.write(_pack(REQ, msgid, method, payload))
                await self._writer.drain()
        except (ConnectionError, OSError) as exc:
            self._pending.pop(msgid, None)
            self.connected = False
            raise ConnectionLost(f"{self.name}: send failed: {exc}")
        if timeout is None:
            return await future
        return await asyncio.wait_for(future, timeout)

    async def close(self) -> None:
        self._closed = True
        self.connected = False
        if self._recv_task is not None:
            self._recv_task.cancel()
            # Await the cancellation so the loop reaps the task — otherwise
            # teardown prints "Task was destroyed but it is pending!" for
            # every client's recv loop (r2 verdict weak #3). asyncio.wait
            # absorbs the task's CancelledError without swallowing a
            # cancellation aimed at close() itself.
            await asyncio.wait({self._recv_task})
            self._recv_task = None
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass


class IoThread:
    """Background asyncio loop thread: the driver/worker 'io service'.

    Equivalent in role to the core worker's io_service threads
    (reference: core_worker.cc io_service_). Sync API code schedules
    coroutines here via run().
    """

    def __init__(self, name: str = "raytpu-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro: Awaitable[Any], timeout: float | None = None) -> Any:
        future = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return future.result(timeout)

    def spawn(self, coro: Awaitable[Any]) -> None:
        asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self) -> None:
        async def _shutdown() -> None:
            tasks = [
                t for t in asyncio.all_tasks(self.loop)
                if t is not asyncio.current_task()
            ]
            for task in tasks:
                task.cancel()
            # Await the cancellations so every task is reaped before the
            # loop stops — a bare call_soon(stop) races the cancellation
            # delivery and leaves "Task was destroyed but it is pending!"
            # warnings behind (r2 verdict weak #3). Bounded: a task whose
            # cleanup awaits something slow (e.g. a retry-backoff dial)
            # must not pin the loop open past the join timeout.
            if tasks:
                await asyncio.wait(tasks, timeout=1.5)
            self.loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(_shutdown(), self.loop)
            self._thread.join(timeout=2)
        except Exception:
            pass

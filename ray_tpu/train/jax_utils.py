"""In-worker jax helpers for JaxTrainer loops.

Role-equivalent of python/ray/train/torch/train_loop_utils.py ::
prepare_model / prepare_data_loader, TPU-first: instead of wrapping a model
in DDP, we build the device mesh, place params with NamedSharding, and sync
gradients — in-jit (psum over ICI, the "xla" path) or eagerly through the
collective group (the "ring" CPU twin).

GSPMD-first training (ISSUE 10): :func:`setup_sharded_training` +
:func:`build_sharded_train_step` make ONE ScalingConfig express data, FSDP,
and tensor parallelism with no user-code changes — the mesh comes from the
config's named axes, per-leaf NamedShardings from parallel.mesh logical
dims + the FSDP shard-largest-axis auto-policy, and the whole step (grads,
optimizer update, new state) compiles as one jax.jit program with explicit
in/out shardings and *sharded optimizer state*. The replicated
:func:`shard_params` path survives as the degenerate pure-data-parallel
case — and refuses models whose train state cannot fit a chip, which is
exactly where the sharded path takes over.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Callable, Iterable, Iterator

import numpy as np

logger = logging.getLogger(__name__)


class MemoryBudgetError(RuntimeError):
    """The planned train state cannot fit the per-device memory budget.

    Raised BEFORE any array is materialized (planning runs on
    jax.eval_shape results), so a doomed config fails in milliseconds
    instead of OOM-killing a TPU host mid-init."""


def device_memory_budget() -> int | None:
    """Per-device memory budget in bytes, or None when unknowable.

    ``RAY_TPU_HBM_BYTES`` overrides (the CPU twin / tests / release gates
    model a chip size this way); otherwise the jax runtime's per-device
    ``bytes_limit`` is used when it reports one. None disables budget
    enforcement — never guess a limit and refuse a runnable config."""
    env = os.environ.get("RAY_TPU_HBM_BYTES")
    if env:
        try:
            return int(float(env))
        except ValueError:
            logger.warning("ignoring unparsable RAY_TPU_HBM_BYTES=%r", env)
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        limit = (stats or {}).get("bytes_limit")
        return int(limit) if limit else None
    except Exception:  # rtlint: disable=swallowed-exception - no jax / no stats: budget unknown, don't enforce
        return None


def _leaf_nbytes(leaf: Any, sharding: Any = None) -> int:
    """This device's resident bytes for one (possibly sharded) leaf."""
    shape = tuple(getattr(leaf, "shape", ()) or np.shape(leaf))
    dtype = np.dtype(getattr(leaf, "dtype", None) or np.asarray(leaf).dtype)
    if sharding is not None and hasattr(sharding, "shard_shape") and shape:
        shape = sharding.shard_shape(shape)
    size = 1
    for dim in shape:
        size *= int(dim)
    return size * dtype.itemsize


def state_bytes_per_device(tree: Any, shardings: Any = None) -> int:
    """Per-device bytes of a pytree of arrays / ShapeDtypeStructs under
    ``shardings`` (None ⇒ fully replicated — every leaf whole)."""
    import jax

    leaves = jax.tree.leaves(tree)
    shard_leaves = (
        jax.tree.leaves(shardings) if shardings is not None else [None] * len(leaves)
    )
    return sum(_leaf_nbytes(l, s) for l, s in zip(leaves, shard_leaves))


def ensure_train_state_fits(
    params: Any,
    shardings: Any = None,
    *,
    optimizer_slots: int = 2,
    workspace_frac: float = 0.2,
    budget: int | None = None,
    what: str = "train state",
) -> int:
    """Refuse configs whose training residency exceeds the device budget.

    Residency model: params + grads + ``optimizer_slots`` optimizer
    moments, all with the params' shardings (grads and Adam moments
    mirror param layout under GSPMD), plus ``workspace_frac`` headroom
    for activations/XLA workspace. Returns the estimated per-device
    bytes; raises :class:`MemoryBudgetError` when over budget."""
    budget = device_memory_budget() if budget is None else budget
    per_state = state_bytes_per_device(params, shardings)
    estimate = int(per_state * (2 + optimizer_slots) * (1.0 + workspace_frac))
    if budget is not None and estimate > budget:
        raise MemoryBudgetError(
            f"{what} needs ~{estimate / 1e9:.1f} GB/device "
            f"(params+grads+{optimizer_slots} optimizer slots "
            f"+{workspace_frac:.0%} workspace) but the per-device budget "
            f"is {budget / 1e9:.1f} GB. Shard it: set fsdp/tp axes in "
            f"ScalingConfig.mesh_axes (see docs/sharding.md) instead of "
            f"the replicated data-parallel path."
        )
    return estimate


def build_mesh(axes: dict[str, int] | None = None, topology=None):
    """Mesh over THIS jax runtime's devices. On a real multi-host gang
    (jax.distributed initialized) that is the whole slice; on the ring
    backend it is the process-local devices. axes={} → 1-D "dp" mesh.

    With ``topology`` (a parallel.topology.SliceTopology), the mesh
    composes cross-slice DCN axes with in-slice ICI axes — the
    multi-slice layout (JaxTrainer's ``topology=`` lands here)."""
    import jax
    from ray_tpu.parallel.mesh import MeshSpec

    if topology is not None:
        return topology.build_mesh()
    devices = jax.devices()
    if not axes:
        axes = {"dp": len(devices)}
    return MeshSpec(dict(axes)).build(devices)


def shard_params(
    params: Any, mesh, logical_dims: Any = None, *, enforce_budget: bool = True
):
    """Place a param pytree onto the mesh. With logical_dims (see
    parallel.mesh.LogicalRules), params get TP/FSDP shardings; without,
    they are replicated — the degenerate pure-data-parallel case.

    The replicated path refuses models whose training residency (params
    + grads + Adam moments) exceeds the per-device budget: replication
    cannot fit them by construction, and the failure should be a clear
    refusal pointing at the sharded path, not a mid-init host OOM."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ray_tpu.parallel.mesh import LogicalRules

    if logical_dims is not None:
        shardings = LogicalRules().tree_shardings(logical_dims, mesh)
        if enforce_budget:
            ensure_train_state_fits(
                params, shardings, what="sharded train state"
            )
        return jax.device_put(params, shardings)
    if enforce_budget:
        ensure_train_state_fits(params, None, what="replicated train state")
    return jax.device_put(
        params, jax.tree.map(lambda _: NamedSharding(mesh, P()), params)
    )


def _flatten_tree(grads: Any):
    """(leaves, treedef, flat f32 vector) for a grad pytree."""
    import jax

    leaves, treedef = jax.tree.flatten(grads)
    flat = np.concatenate([np.asarray(x, np.float32).ravel() for x in leaves])
    return leaves, treedef, flat


def _unflatten_tree(flat: np.ndarray, leaves, treedef) -> Any:
    """Inverse of :func:`_flatten_tree`, restoring leaf shapes/dtypes."""
    import jax

    out, offset = [], 0
    for leaf in leaves:
        # prod(()) is already 1 for scalars; a genuinely empty leaf
        # (size 0) must stay 0 so the reshape below round-trips it.
        size = int(np.prod(np.shape(leaf), dtype=np.int64))
        out.append(
            flat[offset : offset + size].reshape(np.shape(leaf)).astype(
                np.asarray(leaf).dtype
            )
        )
        offset += size
    return jax.tree.unflatten(treedef, out)


def sync_gradients(grads: Any, group_name: str) -> Any:
    """Eager cross-worker gradient mean for the ring backend. (On the xla
    backend gradients sync in-jit via psum — never call this there.)

    Quantized wire compression is transparent here: it lives in the
    group's CollectiveConfig (ScalingConfig.collective_config), not in
    the call site."""
    from ray_tpu.util.collective import collective

    group = collective.get_group(group_name)
    if group.world_size == 1:
        return grads
    leaves, treedef, flat = _flatten_tree(grads)
    flat = np.asarray(group.allreduce(flat)) / group.world_size
    return _unflatten_tree(flat, leaves, treedef)


class GradientSyncHandle:
    """An in-flight overlapped gradient sync (see begin_gradient_sync)."""

    def __init__(self, inner, per_device_leaves, treedef, denom):
        self._inner = inner
        self._leaves = per_device_leaves[0]
        self._treedef = treedef
        self._denom = denom
        self.stats: dict[str, float] = {}

    def result(self) -> Any:
        """Fence: block until every bucket lands, record the exposed
        comm time, and return the globally-AVERAGED grad pytree."""
        import jax
        from ray_tpu.util.collective import bucketing

        segments = self._inner.fence()
        self.stats = dict(self._inner.stats)
        out: list = [None] * len(self._leaves)
        for bucket, segment in zip(self._inner.buckets, segments):
            for i, arr in bucketing.scatter_segment(
                np.asarray(segment, np.float32) / self._denom,
                self._leaves,
                bucket,
            ).items():
                out[i] = arr
        return jax.tree.unflatten(self._treedef, out)


def begin_gradient_sync(
    per_device_grads: list,
    group_name: str,
    *,
    bucket_bytes: int | None = None,
) -> GradientSyncHandle:
    """Launch a bucketed ASYNC gradient sync and return immediately.

    The overlap half of :func:`sync_gradients_sharded`: the grad pytree
    is partitioned into ~``bucket_bytes`` buckets (reverse-topological —
    last-layer grads, which backward produces first, fly first) and each
    bucket's quantized hierarchical allreduce launches on a background
    thread. The caller keeps working (later microbatches, metrics, host
    logging) and fences ONLY at the optimizer step via
    ``handle.result()`` — the fence-blocked wall time lands in the new
    ``comm_exposed_s`` StepStats phase while the total ``collective_s``
    stays, which is exactly how the flight recorder proves the overlap.
    """
    import jax
    from ray_tpu.util.collective import collective, overlap

    group = collective.get_group(group_name)
    per_device_leaves = []
    treedef = None
    for grads in per_device_grads:
        leaves, treedef = jax.tree.flatten(grads)
        per_device_leaves.append([np.asarray(l) for l in leaves])
    denom = group.world_size * len(per_device_leaves)
    inner = overlap.launch_bucketed_allreduce(
        group, per_device_leaves, bucket_bytes
    )
    return GradientSyncHandle(inner, per_device_leaves, treedef, denom)


def sync_gradients_sharded(
    per_device_grads: list,
    group_name: str,
    *,
    overlap: bool | None = None,
    bucket_bytes: int | None = None,
) -> Any:
    """Two-tier gradient mean for hierarchical-backend gangs: one grad
    pytree PER LOCAL DEVICE in, the globally-averaged pytree out.

    Tier 1 reduces the local shards in one jit (psum over ICI); tier 2
    rides the DCN ring with this group's CollectiveConfig (so int8/fp8
    wire compression applies only to the cross-host hop). Falls back to
    host-mean + :func:`sync_gradients` on non-hierarchical groups.

    ``overlap=True`` (or ``CollectiveConfig(overlap=True)`` with
    ``overlap=None`` here) takes the bucketed async path: the sync is
    launched bucket-by-bucket and fenced before returning, so buckets
    overlap EACH OTHER on the wire; callers that can put work between
    launch and fence should use :func:`begin_gradient_sync` directly.
    """
    from ray_tpu.util.collective import collective
    from ray_tpu.util.collective import overlap as overlap_mod

    group = collective.get_group(group_name)
    if overlap is None:
        overlap = bool(getattr(group.config, "overlap", False))
    if overlap and overlap_mod.supports_overlap(group):
        handle = begin_gradient_sync(
            per_device_grads, group_name, bucket_bytes=bucket_bytes
        )
        return handle.result()
    flats = []
    leaves = treedef = None
    for grads in per_device_grads:
        leaves, treedef, flat = _flatten_tree(grads)
        flats.append(flat)
    n_local = len(flats)
    denom = group.world_size * n_local
    if not hasattr(group, "allreduce_sharded"):
        total = np.sum(np.stack(flats), axis=0)
        if group.world_size > 1:
            total = np.asarray(group.allreduce(total))
        return _unflatten_tree(total / denom, leaves, treedef)
    flat = np.asarray(group.allreduce_sharded(flats)) / denom
    return _unflatten_tree(flat, leaves, treedef)


def grad_psum(x, axis: str = "dp", topology=None):
    """The default in-jit gradient reduce (use inside shard_map/jit).

    Single-slice meshes psum over ``axis``; with a SliceTopology the
    reduce is placed tier by tier via ``hierarchical_psum`` — ICI first,
    then DCN — so the compiler never routes a collective-heavy reduce
    over the slow tier. build_mesh(topology=...) callers pass the same
    topology here to get the matching reduction order."""
    import jax

    if topology is not None:
        return topology.hierarchical_psum(x)
    return jax.lax.psum(x, axis)


def shard_batch(batch: Any, mesh, axis: str = "dp"):
    """device_put a host batch with batch-dim sharding over `axis`."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda x: jax.device_put(x, spec), batch)


def iter_global_batches(
    it: Iterable, *, world_rank: int, world_size: int
) -> Iterator:
    """Stride an iterable of batches across ranks (the ring-backend data
    path; ray_tpu.data shards upstream instead)."""
    for i, batch in enumerate(it):
        if i % world_size == world_rank:
            yield batch


# ---------------------------------------------------------------------------
# GSPMD-first training (ISSUE 10)
# ---------------------------------------------------------------------------
def mesh_factorization(mesh) -> dict[str, int]:
    """The (dp, fsdp, tp, pp) factorization a mesh expresses — stamped
    into Result.metrics so every run records how it was parallelized."""
    shape = dict(getattr(mesh, "shape", {}) or {})
    return {
        "dp": int(shape.get("dp", 1)),
        "fsdp": int(shape.get("fsdp", 1)),
        "tp": int(shape.get("tp", 1)),
        "pp": int(shape.get("pp", 1)),
    }


@dataclasses.dataclass
class ShardedTrainSetup:
    """Everything :func:`build_sharded_train_step` needs, planned and
    materialized by :func:`setup_sharded_training`."""

    mesh: Any
    params: Any
    opt_state: Any
    param_shardings: Any
    opt_shardings: Any
    factorization: dict[str, int]
    state_bytes_per_device: int

    def shard_batch(self, batch: Any) -> Any:
        """device_put a host batch with its leading dim split over the
        data axes (dp × fsdp) of this setup's mesh."""
        from ray_tpu.parallel.mesh import shard_batch as _shard

        return _shard(batch, self.mesh)


def _session_mesh():
    """Mesh from the active train session's config, or all local devices."""
    from ray_tpu.train._internal import session as session_mod

    if session_mod.in_session():
        ctx = session_mod.get_session().ctx
        return build_mesh(
            dict(ctx.mesh or {}), topology=ctx.slice_topology
        )
    return build_mesh()


def _optimizer_state_shardings(
    optimizer: Any, param_shapes: Any, param_shardings: Any, mesh
):
    """Shardings for the optimizer state, matching the params'.

    Primary path: compile ``optimizer.init`` with the params' shardings
    and read XLA's propagated ``output_shardings`` — Adam moments come
    out sharded exactly like their params, counters replicated. Fallback
    (older jax without output_shardings, exotic optimizers): match
    optimizer leaves to param leaves by (shape, dtype), replicating
    anything unmatched."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def normalize(s):
        # Leaves with no data dependence on the params (step counters)
        # come back single-device from the propagation probe; pin every
        # sharding that doesn't span the mesh to replicated-on-mesh.
        if getattr(s, "num_devices", 0) == mesh.devices.size:
            return s
        return NamedSharding(mesh, P())

    try:
        compiled = (
            jax.jit(optimizer.init, in_shardings=(param_shardings,))
            .lower(param_shapes)
            .compile()
        )
        return jax.tree.map(normalize, compiled.output_shardings)
    except Exception:  # rtlint: disable=swallowed-exception - propagation probe failed: shape-match fallback below
        logger.debug(
            "optimizer sharding propagation failed; using shape match",
            exc_info=True,
        )
    by_shape: dict[tuple, Any] = {}
    for leaf, sh in zip(
        jax.tree.leaves(param_shapes), jax.tree.leaves(param_shardings)
    ):
        key = (tuple(leaf.shape), np.dtype(leaf.dtype))
        by_shape.setdefault(key, sh)
    opt_shapes = jax.eval_shape(optimizer.init, param_shapes)

    def pick(leaf):
        key = (tuple(leaf.shape), np.dtype(leaf.dtype))
        return by_shape.get(key, NamedSharding(mesh, P()))

    return jax.tree.map(pick, opt_shapes)


def setup_sharded_training(
    init_fn: Callable[[], Any],
    optimizer: Any,
    *,
    mesh=None,
    logical_dims: Any = None,
    rules: Any = None,
    fsdp_axis: str = "fsdp",
    enforce_budget: bool = True,
) -> ShardedTrainSetup:
    """Plan and materialize a sharded train state from ONE mesh.

    ``init_fn`` is a zero-arg callable returning the param pytree (close
    over config + PRNG key). The flow is plan-before-materialize:

      1. ``jax.eval_shape(init_fn)`` — shapes only, no arrays;
      2. per-leaf NamedShardings via parallel.mesh.auto_shard_specs
         (logical-dim TP rules + the FSDP shard-largest-axis policy;
         axes absent from the mesh degrade to replication, so a pure-dp
         mesh reproduces the replicated path);
      3. memory-budget check on the PLAN — a config that cannot fit is
         refused before any init work happens;
      4. ``jax.jit(init_fn, out_shardings=...)`` — every device
         materializes only its own param shards (a 1B model never
         exists unsharded anywhere);
      5. optimizer state is initialized the same way, with shardings
         propagated from the params.
    """
    import jax

    from ray_tpu.parallel.mesh import auto_shard_specs

    # Sharding-invariant RNG (the modern jax default): without this, the
    # SAME init_fn produces DIFFERENT weights under different
    # out_shardings — breaking the contract that one config change
    # refactorizes a run without changing its math (and the elastic
    # resize-parity guarantee with it).
    jax.config.update("jax_threefry_partitionable", True)
    if mesh is None:
        mesh = _session_mesh()
    param_shapes = jax.eval_shape(init_fn)
    param_shardings = auto_shard_specs(
        param_shapes,
        mesh,
        logical_dims=logical_dims,
        rules=rules,
        fsdp_axis=fsdp_axis,
    )
    estimate = ensure_train_state_fits(
        param_shapes,
        param_shardings,
        what="sharded train state",
        budget=None if enforce_budget else float("inf"),
    )
    params = jax.jit(init_fn, out_shardings=param_shardings)()
    opt_shardings = _optimizer_state_shardings(
        optimizer, param_shapes, param_shardings, mesh
    )
    opt_state = jax.jit(optimizer.init, out_shardings=opt_shardings)(params)
    return ShardedTrainSetup(
        mesh=mesh,
        params=params,
        opt_state=opt_state,
        param_shardings=param_shardings,
        opt_shardings=opt_shardings,
        factorization=mesh_factorization(mesh),
        state_bytes_per_device=estimate,
    )


def build_sharded_train_step(
    loss_fn: Callable[[Any, Any], Any],
    optimizer: Any,
    setup: ShardedTrainSetup,
    *,
    group_name: str | None = None,
    donate: bool = True,
) -> Callable[[Any, Any, Any], tuple[Any, Any, Any]]:
    """Compile ``loss_fn(params, batch) -> scalar`` into one train step.

    Returns ``step(params, opt_state, batch) -> (params, opt_state,
    loss)``. On one jax runtime (real slices via jax.distributed, or the
    in-worker mesh) the WHOLE step — grads, cross-device reductions,
    optimizer update — is one jit program with explicit out_shardings
    and donated state: GSPMD inserts every collective.

    ``group_name`` handles the ring CPU twin's multi-process gangs: each
    worker owns a private mesh, so cross-WORKER gradient averaging runs
    eagerly through the collective group between a grad jit and an
    apply jit (still sharded within the worker). That eager seam is also
    where the step profiler's fwd/bwd/opt attribution lives (ISSUE 20):
    the forward runs as ``jax.vjp`` THROUGH jit — the returned vjp
    closure is a ``tree_util.Partial`` pytree carrying the residuals
    across the jit boundary — so forward and backward are separate
    programs wrapped in ``step_annotation`` scopes. The fused
    single-runtime path stays ONE program (GSPMD inserts the collectives
    there; splitting it would forfeit cross-phase fusion), so it reports
    an unsplit ``compute`` remainder."""
    import jax

    from ray_tpu.train._internal.step_stats import step_annotation

    donate_args = (0, 1) if donate else ()
    param_sh, opt_sh = setup.param_shardings, setup.opt_shardings

    def apply_update(params, opt_state, grads):
        updates, new_opt = optimizer.update(grads, opt_state, params)
        new_params = jax.tree.map(
            lambda p, u: (p + u.astype(p.dtype)), params, updates
        )
        return new_params, new_opt

    cross_worker = False
    if group_name:
        from ray_tpu.util.collective import collective

        cross_worker = collective.get_group(group_name).world_size > 1

    if not cross_worker:
        def fused(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            new_params, new_opt = apply_update(params, opt_state, grads)
            return new_params, new_opt, loss

        return jax.jit(
            fused,
            out_shardings=(param_sh, opt_sh, None),
            donate_argnums=donate_args,
        )

    if _vjp_through_jit_supported():
        # vjp residuals may shard differently from params; out_shardings
        # stays default on fwd so GSPMD propagates them. The unused batch
        # cotangent inside bwd is dead code XLA eliminates.
        fwd_fn = jax.jit(lambda p, b: jax.vjp(loss_fn, p, b))
        bwd_fn = jax.jit(lambda vf, ct: vf(ct)[0], out_shardings=param_sh)
        grad_fn = None
    else:
        fwd_fn = bwd_fn = None
        grad_fn = jax.jit(
            jax.value_and_grad(loss_fn), out_shardings=(None, param_sh)
        )
    apply_fn = jax.jit(
        apply_update,
        out_shardings=(param_sh, opt_sh),
        donate_argnums=donate_args,
    )

    # Attribution syncs below sit on boundaries that are already serial:
    # bwd consumes fwd's residuals, sync_gradients blocks on the grads,
    # and next step's fwd consumes the applied params — so each
    # block_until_ready closes a dependency edge the device queue
    # enforces anyway, moving the wait INTO the phase that caused it
    # instead of smearing it into the next annotation.
    def step(params, opt_state, batch):
        if grad_fn is not None:
            # Probe said vjp can't cross this jit boundary: fwd+bwd stay
            # one program, attributed to bwd (backward dominates it).
            with step_annotation("bwd", phase="bwd"):
                loss, grads = grad_fn(params, batch)
                jax.block_until_ready(grads)  # rtlint: disable=host-sync-in-step - attribution boundary; sync_gradients blocks on grads next anyway
        else:
            with step_annotation("fwd", phase="fwd"):
                loss, vjp_fn = fwd_fn(params, batch)
                jax.block_until_ready(loss)  # rtlint: disable=host-sync-in-step - attribution boundary; bwd consumes the residuals next anyway
            with step_annotation("bwd", phase="bwd"):
                grads = bwd_fn(vjp_fn, jax.numpy.ones_like(loss))
                jax.block_until_ready(grads)  # rtlint: disable=host-sync-in-step - attribution boundary; sync_gradients blocks on grads next anyway
        with step_annotation("grad_sync"):
            # Phase accounting happens inside the collective layer
            # (collective_s / comm_exposed_s) — the annotation only names
            # the scope on the merged trace.
            grads = sync_gradients(grads, group_name)
            grads = jax.device_put(grads, param_sh)
        with step_annotation("opt", phase="opt"):
            params, opt_state = apply_fn(params, opt_state, grads)
            jax.block_until_ready(params)  # rtlint: disable=host-sync-in-step - attribution boundary; next fwd consumes params anyway
        return params, opt_state, loss

    return step


_VJP_THROUGH_JIT: bool | None = None


def _vjp_through_jit_supported() -> bool:
    """One cached probe: can a ``jax.vjp`` closure cross a jit boundary
    (returned from one jit program, applied inside another)? Modern jax
    returns it as a ``tree_util.Partial`` pytree, so yes — but the split
    train step must degrade to fused value_and_grad, not crash, on a
    runtime where it can't."""
    global _VJP_THROUGH_JIT
    if _VJP_THROUGH_JIT is None:
        try:
            import jax
            import jax.numpy as jnp

            x = jnp.arange(2.0)
            loss, vf = jax.jit(lambda v: jax.vjp(lambda u: (u * u).sum(), v))(x)
            (grad,) = jax.jit(lambda f, ct: f(ct))(vf, jnp.ones_like(loss))
            _VJP_THROUGH_JIT = bool(abs(float(grad[1]) - 2.0) < 1e-5)
        except Exception:  # rtlint: disable=swallowed-exception - feature probe: any failure means "use the fused fallback"
            _VJP_THROUGH_JIT = False
    return _VJP_THROUGH_JIT


def save_sharded_state(
    params: Any, opt_state: Any, *, extra: dict | None = None
):
    """Persist (params, opt_state) as one committed checkpoint.

    Rides the two-phase committed-checkpoint protocol (per-rank DONE
    markers + CRC inventory), saving each leaf's GLOBAL index with its
    shards — which is what lets :func:`restore_sharded_state` re-place
    the state onto ANY (dp, fsdp, tp) factorization on restore."""
    from ray_tpu.train.checkpoint import save_pytree_checkpoint

    return save_pytree_checkpoint(
        {"params": params, "opt_state": opt_state}, extra=extra
    )


def restore_sharded_state(
    checkpoint: Any, setup: ShardedTrainSetup
) -> tuple[Any, Any, dict]:
    """Load a committed checkpoint onto ``setup``'s mesh — the saved
    factorization need not match (elastic resize: dp=4 → dp=2×fsdp=2
    restores exactly). Returns (params, opt_state, extra)."""
    from ray_tpu.train.checkpoint import load_pytree_checkpoint

    tree, extra = load_pytree_checkpoint(
        checkpoint,
        {"params": setup.param_shardings, "opt_state": setup.opt_shardings},
    )
    return tree["params"], tree["opt_state"], extra
